"""Recipe-fidelity convergence: full-schedule training + resume parity.

The reference's convergence ground truth is its published solver recipes —
ResNet-50's is poly decay with power 2.0, momentum 0.9, weight decay 1e-4
(reference models/resnet50/solver.prototxt:1-36) — and its solver tests
assert that a snapshot/restore round-trip continues the *identical*
trajectory (reference src/caffe/test/test_gradient_based_solver.cpp:543-552).

This file proves both properties for the TPU build, on the synthetic
cluster task (no dataset egress), at three levels the reference cannot test
(it has no fake cluster):

1. the recipe runs TO COMPLETION (the whole poly schedule, lr -> 0) and
   converges;
2. a mid-run snapshot + restore reproduces the remaining trajectory
   BIT-EXACTLY (same losses, same final params) — float32 binaryproto
   state round-trips losslessly and the jitted step is deterministic;
3. a snapshot taken on one mesh shape resumes on another (1 <-> 8 virtual
   devices) and lands on the uninterrupted trajectory to within reduction
   -order tolerance — the checkpoint is topology-portable, which is what
   lets a 16-chip run restart on a different slice.
"""

import os
import sys

import numpy as np
import jax.numpy as jnp

from caffe_mpi_tpu.parallel import MeshPlan
from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from caffe_mpi_tpu.solver import Solver
from caffe_mpi_tpu.solver import lr_policy

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, _ROOT)

# Small BN-free convnet so state is params-only and bit-exact resume is a
# meaningful assertion (BatchNorm running stats round-trip too, but their
# update order vs. the optimizer's is covered by test_layers/test_solver).
NET = """
name: "recipe_net"
layer { name: "in" type: "Input" top: "data" top: "label"
        input_param { shape { dim: 32 dim: 3 dim: 16 dim: 16 }
                      shape { dim: 32 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "c1"
        convolution_param { num_output: 8 kernel_size: 3 pad: 1
          weight_filler { type: "msra" } } }
layer { name: "relu1" type: "ReLU" bottom: "c1" top: "c1" }
layer { name: "pool1" type: "Pooling" bottom: "c1" top: "p1"
        pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "ip1" type: "InnerProduct" bottom: "p1" top: "h"
        inner_product_param { num_output: 32
          weight_filler { type: "xavier" } } }
layer { name: "relu2" type: "ReLU" bottom: "h" top: "h" }
layer { name: "ip2" type: "InnerProduct" bottom: "h" top: "logits"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
        bottom: "label" top: "loss" }
"""

# The ResNet-50 recipe SHAPE at toy scale: poly power 2.0, momentum 0.9,
# wd 1e-4 (reference models/resnet50/solver.prototxt:1-36). max_iter is the
# full schedule length — the test runs all of it.
MAX_ITER = 64
RECIPE = (
    'base_lr: 0.05 lr_policy: "poly" power: 2.0 momentum: 0.9 '
    f'weight_decay: 0.0001 max_iter: {MAX_ITER} type: "SGD" '
    'random_seed: 7 display: 0'
)


def make_solver(mesh=None):
    sp = SolverParameter.from_text(RECIPE)
    sp.net_param = NetParameter.from_text(NET)
    return Solver(sp, mesh=mesh)


def make_batches():
    """One fixed pass over the synthetic cluster task; identical feeds for
    every run so trajectory comparisons isolate the solver/mesh."""
    from examples.common import synthetic_clusters

    imgs, labels = synthetic_clusters(32 * MAX_ITER, (3, 16, 16), seed=5,
                                      classes=4)
    imgs = imgs.astype(np.float32) / 255.0
    return [
        {"data": jnp.asarray(imgs[32 * i: 32 * (i + 1)]),
         "label": jnp.asarray(labels[32 * i: 32 * (i + 1)].astype(np.int32))}
        for i in range(MAX_ITER)
    ]


def run(solver, batches, start, n):
    """n iterations one at a time, returning the per-iteration loss
    trajectory (host floats; fine on CPU)."""
    losses = []
    for i in range(start, start + n):
        losses.append(solver.step(1, lambda it, i=i: batches[i]))
    return losses


def flat_params(solver):
    return {f"{l}/{p}": np.asarray(v)
            for l, lp in solver.params.items() for p, v in lp.items()}


class TestRecipeFidelity:
    def test_poly_schedule_closed_form(self):
        """lr follows base_lr * (1 - it/max_iter)^power exactly
        (reference sgd_solver.cpp:24-65 'poly')."""
        sp = SolverParameter.from_text(RECIPE)
        for it in (0, 1, MAX_ITER // 2, MAX_ITER - 1):
            expect = 0.05 * (1.0 - it / MAX_ITER) ** 2.0
            got = float(lr_policy.learning_rate(sp, jnp.int32(it)))
            assert got == np.float32(expect) or abs(got - expect) < 1e-9

    def test_full_schedule_resume_and_mesh_swap(self, tmp_path):
        batches = make_batches()
        half = MAX_ITER // 2

        # --- uninterrupted single-device run of the full schedule
        ref = make_solver()
        ref_losses = run(ref, batches, 0, MAX_ITER)
        ref_final = flat_params(ref)

        # the recipe converges: last losses well below the first
        assert np.mean(ref_losses[-8:]) < 0.25 * ref_losses[0], ref_losses
        assert np.mean(ref_losses[-8:]) < 0.5, ref_losses

        # --- (a) mid-run snapshot, restore, finish: bit-exact trajectory
        a = make_solver()
        a.sp.snapshot_prefix = str(tmp_path / "mid")
        pre_losses = run(a, batches, 0, half)
        np.testing.assert_array_equal(np.asarray(pre_losses),
                                      np.asarray(ref_losses[:half]))
        path = a.snapshot()

        b = make_solver()
        b.restore(path)
        assert b.iter == half  # poly lr continues from the right spot
        post_losses = run(b, batches, half, MAX_ITER - half)
        np.testing.assert_array_equal(np.asarray(post_losses),
                                      np.asarray(ref_losses[half:]))
        for k, v in flat_params(b).items():
            np.testing.assert_array_equal(v, ref_final[k], err_msg=k)

        # --- (b) the same snapshot resumes on an 8-device mesh: the
        # trajectory rejoins the single-device one to within reduction-
        # order tolerance (the DP allreduce is a mean, not an approximation
        # — reference test_gradient_based_solver.cpp:484-485 analogue)
        m = make_solver(mesh=MeshPlan.data_parallel())
        m.restore(path)
        m_losses = run(m, batches, half, MAX_ITER - half)
        np.testing.assert_allclose(np.asarray(m_losses),
                                   np.asarray(ref_losses[half:]),
                                   rtol=5e-4, atol=1e-5)
        m_final = flat_params(m)
        for k, v in m_final.items():
            np.testing.assert_allclose(v, ref_final[k], rtol=2e-3,
                                       atol=1e-5, err_msg=k)

        # --- (c) reverse direction: snapshot taken ON the mesh restores
        # onto a single device and finishes the schedule
        m2 = make_solver(mesh=MeshPlan.data_parallel())
        m2.sp.snapshot_prefix = str(tmp_path / "mesh")
        run(m2, batches, 0, half)
        mpath = m2.snapshot()

        s2 = make_solver()
        s2.restore(mpath)
        assert s2.iter == half
        s2_losses = run(s2, batches, half, MAX_ITER - half)
        np.testing.assert_allclose(np.asarray(s2_losses),
                                   np.asarray(ref_losses[half:]),
                                   rtol=5e-4, atol=1e-5)
        for k, v in flat_params(s2).items():
            np.testing.assert_allclose(v, ref_final[k], rtol=2e-3,
                                       atol=1e-5, err_msg=k)
