"""Solver tests — mirrors reference test_gradient_based_solver.cpp:
closed-form update checks on a least-squares net, snapshot/restore
round-trip, LR policies, and an end-to-end LeNet-style convergence run.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.proto import SolverParameter
from caffe_mpi_tpu.solver import Solver
from caffe_mpi_tpu.solver.lr_policy import learning_rate, momentum

# tiny least-squares net: y = Wx + b, EuclideanLoss against targets
LSQ_NET = """
name: "lsq"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 4 dim: 3 } shape { dim: 4 dim: 1 } } }
layer { name: "ip" type: "InnerProduct" bottom: "x" top: "pred"
        inner_product_param { num_output: 1
          weight_filler { type: "gaussian" std: 1 }
          bias_filler { type: "gaussian" std: 1 } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "pred" bottom: "t" top: "l" }
"""


def make_solver(extra: str = "", net: str = LSQ_NET) -> Solver:
    sp = SolverParameter.from_text(
        f'base_lr: 0.1 max_iter: 50 lr_policy: "fixed" display: 0\n{extra}'
    )
    sp.net_param = __import__(
        "caffe_mpi_tpu.proto.config", fromlist=["NetParameter"]
    ).NetParameter.from_text(net)
    return Solver(sp)


def lsq_feeds(rng):
    x = rng.randn(4, 3).astype(np.float32)
    t = (x @ np.array([[1.0], [-2.0], [0.5]]) + 0.3).astype(np.float32)
    return {"x": jnp.asarray(x), "t": jnp.asarray(t)}


class TestClosedFormUpdates:
    """One solver step must equal the hand-computed Caffe update rule."""

    def _grads(self, solver, feeds):
        def loss_fn(p):
            return solver.net.apply(p, solver.net_state, feeds, train=True,
                                    rng=jax.random.PRNGKey(1))[2]
        return jax.grad(loss_fn)(solver.params)

    @pytest.mark.parametrize("stype,extra", [
        ("SGD", "momentum: 0.9"),
        ("SGD", "momentum: 0.9 weight_decay: 0.01"),
        ("Nesterov", "momentum: 0.9"),
        ("AdaGrad", ""),
        ("RMSProp", "rms_decay: 0.95"),
        ("AdaDelta", "momentum: 0.95"),
        ("Adam", "momentum: 0.9 momentum2: 0.999"),
    ])
    def test_first_step(self, stype, extra, rng):
        solver = make_solver(f'type: "{stype}" {extra}')
        feeds = lsq_feeds(rng)
        w0 = np.array(solver.params["ip"]["weight"], np.float64)
        g = np.array(self._grads(solver, feeds)["ip"]["weight"], np.float64)
        sp = solver.sp
        wd = sp.weight_decay
        g = g + wd * w0
        lr, mom = 0.1, sp.momentum
        if stype in ("SGD", "Nesterov"):
            hist = lr * g  # zero initial history
            expect = w0 - (hist if stype == "SGD"
                           else (1 + mom) * hist)
        elif stype == "AdaGrad":
            expect = w0 - lr * g / (np.sqrt(g * g) + sp.delta)
        elif stype == "RMSProp":
            h = 0.05 * g * g
            expect = w0 - lr * g / (np.sqrt(h) + sp.delta)
        elif stype == "AdaDelta":
            delta = max(sp.delta, 1e-3)
            h = 0.05 * g * g
            upd = g * np.sqrt(delta / (delta + h))
            expect = w0 - lr * upd
        elif stype == "Adam":
            b1, b2 = 0.9, 0.999
            m = (1 - b1) * g
            v = (1 - b2) * g * g
            corr = np.sqrt(1 - b2) / (1 - b1)
            expect = w0 - lr * corr * m / (np.sqrt(v) + 1e-4)
        solver.step(1, lambda it: feeds)
        got = np.array(solver.params["ip"]["weight"], np.float64)
        np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-6)

    def test_iter_size_accumulation(self, rng):
        """iter_size=2 with the same data must equal iter_size=1 updates
        (grads averaged) — reference test_gradient_based_solver.cpp
        TestSnapshotShare/iter_size cases."""
        feeds = lsq_feeds(rng)
        s1 = make_solver('type: "SGD" momentum: 0.9')
        s2 = make_solver('type: "SGD" momentum: 0.9 iter_size: 2')
        s2.params = jax.tree.map(lambda x: jnp.array(x, copy=True), s1.params)
        s1.step(1, lambda it: feeds)
        s2.step(1, lambda it: feeds)
        np.testing.assert_allclose(np.array(s1.params["ip"]["weight"]),
                                   np.array(s2.params["ip"]["weight"]),
                                   rtol=1e-5)

    def test_clip_gradients(self, rng):
        feeds = lsq_feeds(rng)
        s = make_solver('type: "SGD" clip_gradients: 0.001')
        g = self._grads(s, feeds)
        gnorm = float(jnp.sqrt(sum(jnp.sum(jnp.square(x))
                                   for x in jax.tree.leaves(g))))
        assert gnorm > 0.001
        w0 = np.array(s.params["ip"]["weight"], np.float64)
        gw = np.array(g["ip"]["weight"], np.float64)
        s.step(1, lambda it: feeds)
        got = np.array(s.params["ip"]["weight"], np.float64)
        expect = w0 - 0.1 * gw * (0.001 / gnorm)
        np.testing.assert_allclose(got, expect, rtol=1e-3)


class TestLRPolicies:
    def p(self, text):
        return SolverParameter.from_text(text)

    def test_policies(self):
        it = jnp.int32(100)
        cases = [
            ('base_lr: 0.1 lr_policy: "fixed"', 0.1),
            ('base_lr: 0.1 lr_policy: "step" gamma: 0.5 stepsize: 30', 0.1 * 0.5**3),
            ('base_lr: 0.1 lr_policy: "exp" gamma: 0.99', 0.1 * 0.99**100),
            ('base_lr: 0.1 lr_policy: "inv" gamma: 0.1 power: 0.5',
             0.1 * (1 + 0.1 * 100) ** -0.5),
            ('base_lr: 0.1 lr_policy: "multistep" gamma: 0.1 stepvalue: 50 stepvalue: 150',
             0.1 * 0.1),
            ('base_lr: 0.1 lr_policy: "poly" power: 2 max_iter: 200', 0.1 * 0.25),
            ('base_lr: 0.1 lr_policy: "poly" power: 1 max_iter: 200 min_lr: 0.02',
             0.02 + 0.08 * 0.5),
        ]
        for text, expect in cases:
            got = float(learning_rate(self.p(text), it))
            assert got == pytest.approx(expect, rel=1e-5), text

    def test_rampup(self):
        p = self.p('base_lr: 1.0 lr_policy: "fixed" rampup_interval: 100 '
                   'rampup_lr: 0.1')
        assert float(learning_rate(p, jnp.int32(0))) == pytest.approx(0.1)
        assert float(learning_rate(p, jnp.int32(50))) == pytest.approx(0.55)
        assert float(learning_rate(p, jnp.int32(100))) == pytest.approx(1.0)

    def test_momentum_policies(self):
        p = self.p('momentum: 0.5 momentum_policy: "poly" max_momentum: 0.9 '
                   'max_iter: 100')
        assert float(momentum(p, jnp.int32(50))) == pytest.approx(0.7)


class TestTestInterval:
    def test_test_all_during_training(self, rng):
        """test_interval evaluation with train->test weight sharing and
        score averaging (reference solver.cpp:439-540)."""
        from caffe_mpi_tpu.proto.config import NetParameter
        net_text = """
        layer { name: "in" type: "Input" top: "x" top: "t"
                input_param { shape { dim: 8 dim: 6 } shape { dim: 8 } } }
        layer { name: "ip" type: "InnerProduct" bottom: "x" top: "y"
                inner_product_param { num_output: 3
                  weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t"
                top: "l" include { phase: TRAIN } }
        layer { name: "acc" type: "Accuracy" bottom: "y" bottom: "t"
                top: "acc" include { phase: TEST } }
        """
        sp = SolverParameter.from_text(
            'base_lr: 0.2 lr_policy: "fixed" max_iter: 40 type: "SGD" '
            'test_interval: 20 test_iter: 4 test_initialization: false')
        sp.net_param = NetParameter.from_text(net_text)
        solver = Solver(sp)
        templates = rng.randn(3, 6).astype(np.float32)

        def feed(it):
            r = np.random.RandomState(it)
            t = r.randint(0, 3, 8)
            return {"x": jnp.asarray(templates[t] + 0.1 * r.randn(8, 6).astype(np.float32)),
                    "t": jnp.asarray(t)}

        solver.step(40, feed, test_feed_fns=[lambda k: feed(5000 + k)])
        scores = solver.test_all([lambda k: feed(9000 + k)])
        assert scores[0]["acc"] > 0.9

        # score parity: the device-accumulated averages must equal a naive
        # per-iteration host-side average of the test net's outputs
        tnet = solver.test_nets[0]
        naive = {}
        for k in range(4):  # test_iter: 4
            blobs = tnet.apply(solver._shared_params(tnet), solver.net_state,
                               feed(9000 + k), train=False)[0]
            for b in ("acc",):
                naive[b] = naive.get(b, 0.0) + float(jnp.sum(blobs[b]))
        for b in naive:
            assert scores[0][b] == pytest.approx(naive[b] / 4, rel=1e-6)


class TestEndToEnd:
    def test_lsq_converges(self, rng):
        solver = make_solver('type: "SGD" momentum: 0.9 base_lr: 0.02')
        data = [lsq_feeds(rng) for _ in range(8)]
        first = solver.step(1, lambda it: data[it % 8])
        loss = solver.step(100, lambda it: data[it % 8])
        assert loss < first * 0.05, f"no convergence: {first} -> {loss}"

    def test_snapshot_restore_roundtrip(self, rng, tmp_path):
        solver = make_solver('type: "Adam" momentum: 0.9')
        solver.sp.snapshot_prefix = str(tmp_path / "snap")
        data = [lsq_feeds(rng) for _ in range(4)]
        solver.step(5, lambda it: data[it % 4])
        path = solver.snapshot()
        w_before = np.array(solver.params["ip"]["weight"])
        solver.step(3, lambda it: data[it % 4])
        w_after = np.array(solver.params["ip"]["weight"])
        assert not np.allclose(w_before, w_after)

        solver2 = make_solver('type: "Adam" momentum: 0.9')
        solver2.restore(path)
        assert solver2.iter == 5
        np.testing.assert_array_equal(
            np.array(solver2.params["ip"]["weight"]), w_before)
        # resumed training must reproduce the original trajectory
        solver2.step(3, lambda it: data[it % 4])
        np.testing.assert_allclose(np.array(solver2.params["ip"]["weight"]),
                                   w_after, rtol=1e-5)

    def test_async_snapshot_is_point_in_time(self, rng, tmp_path):
        """snapshot(block=False) must capture the state at the trigger
        iteration even while training races ahead — jax arrays are
        immutable, so the captured pytree IS that instant's state."""
        data = [lsq_feeds(rng) for _ in range(4)]

        ref = make_solver('type: "Adam" momentum: 0.9')
        ref.sp.snapshot_prefix = str(tmp_path / "ref")
        ref.step(2, lambda it: data[it % 4])
        ref_path = ref.snapshot()  # blocking, at iter 2

        solver = make_solver('type: "Adam" momentum: 0.9 snapshot: 2')
        solver.sp.snapshot_prefix = str(tmp_path / "async")
        # interval snapshots fire async inside step(); training continues
        solver.step(6, lambda it: data[it % 4])
        solver.wait_snapshots()
        for it in (2, 4, 6):
            assert os.path.exists(tmp_path / f"async_iter_{it}.solverstate")

        # the async iter-2 snapshot equals a blocking snapshot taken by an
        # identical solver stopped at iter 2 — byte for byte
        ref_bytes = (tmp_path / "ref_iter_2.caffemodel").read_bytes()
        async_bytes = (tmp_path / "async_iter_2.caffemodel").read_bytes()
        assert ref_bytes == async_bytes
        s1 = (tmp_path / "ref_iter_2.solverstate").read_bytes()
        s2 = (tmp_path / "async_iter_2.solverstate").read_bytes()
        # the embedded learned_net filename differs (prefix); compare by
        # restoring both and checking identical continued training
        a = make_solver('type: "Adam" momentum: 0.9')
        a.restore(str(tmp_path / "async_iter_2.solverstate"))
        b = make_solver('type: "Adam" momentum: 0.9')
        b.restore(ref_path)
        assert a.iter == b.iter == 2
        a.step(3, lambda it: data[it % 4])
        b.step(3, lambda it: data[it % 4])
        np.testing.assert_allclose(np.array(a.params["ip"]["weight"]),
                                   np.array(b.params["ip"]["weight"]),
                                   rtol=1e-6)
        assert len(s1) and len(s2)

    def test_async_snapshot_failure_is_raised(self, rng, tmp_path):
        """A failed background write must surface, not exit 0 with the
        user believing checkpoints exist."""
        target = tmp_path / "blocked"
        target.write_text("a file, not a directory")
        solver = make_solver('type: "SGD" momentum: 0.9 snapshot: 2')
        solver.sp.snapshot_prefix = str(target / "s")  # mkdir will fail
        data = [lsq_feeds(rng) for _ in range(4)]
        with pytest.raises(RuntimeError, match="async snapshot failed"):
            solver.step(2, lambda it: data[it % 4])
            solver.wait_snapshots()

    def test_solverstate_is_reference_binaryproto(self, rng, tmp_path):
        """The .solverstate on disk is the reference's SolverState wire
        format (caffe.proto:303-308): parse it with the raw codec, check
        slot-major Adam history (m bank then v bank, adam_solver.cu:37-39),
        then restore it into a fresh solver and verify identical
        continued training."""
        from caffe_mpi_tpu.io import load_solverstate
        solver = make_solver('type: "Adam" momentum: 0.9')
        solver.sp.snapshot_prefix = str(tmp_path / "snap")
        data = [lsq_feeds(rng) for _ in range(4)]
        solver.step(7, lambda it: data[it % 4])
        path = solver.snapshot()
        assert path.endswith(".solverstate") and not path.endswith(".npz")

        it, learned_net, history, _ = load_solverstate(path)
        assert it == 7
        assert learned_net.endswith("_iter_7.caffemodel")
        # 2 params (weight, bias) x 2 Adam slots, slot-major
        assert len(history) == 4
        m_w = np.asarray(solver.opt_state["ip"]["weight"][0])
        v_w = np.asarray(solver.opt_state["ip"]["weight"][1])
        np.testing.assert_allclose(history[0].reshape(m_w.shape), m_w,
                                   rtol=1e-6)
        np.testing.assert_allclose(history[2].reshape(v_w.shape), v_w,
                                   rtol=1e-6)

        # restored solver must continue exactly like the original
        solver2 = make_solver('type: "Adam" momentum: 0.9')
        solver2.restore(path)
        l1 = solver.step(3, lambda it: data[it % 4])
        l2 = solver2.step(3, lambda it: data[it % 4])
        np.testing.assert_allclose(l1, l2, rtol=1e-6)

    def test_solverstate_hdf5_roundtrip(self, rng, tmp_path):
        solver = make_solver('type: "SGD" momentum: 0.9')
        solver.sp.snapshot_prefix = str(tmp_path / "snap")
        solver.sp.snapshot_format = "HDF5"
        data = [lsq_feeds(rng) for _ in range(4)]
        solver.step(4, lambda it: data[it % 4])
        path = solver.snapshot()
        assert path.endswith(".solverstate.h5")
        solver2 = make_solver('type: "SGD" momentum: 0.9')
        solver2.restore(path)
        assert solver2.iter == 4
        np.testing.assert_allclose(
            np.asarray(solver2.opt_state["ip"]["weight"][0]),
            np.asarray(solver.opt_state["ip"]["weight"][0]), rtol=1e-6)

    def test_solverstate_bank_mismatch_rejected(self, rng, tmp_path):
        """Resuming an Adam snapshot into an SGD solver must fail loudly
        (reference CHECK_EQ on history size, sgd_solver.cpp:324), not load
        the m bank as momentum and drop v."""
        solver = make_solver('type: "Adam" momentum: 0.9')
        solver.sp.snapshot_prefix = str(tmp_path / "snap")
        data = [lsq_feeds(rng) for _ in range(2)]
        solver.step(2, lambda it: data[it % 2])
        path = solver.snapshot()
        sgd = make_solver('type: "SGD" momentum: 0.9')
        with pytest.raises(ValueError, match="different solver type"):
            sgd.restore(path)

    def test_reference_written_solverstate_restores(self, rng, tmp_path):
        """Simulate a snapshot produced by a reference build (raw wire
        encode, independent of Solver) and resume from it."""
        from caffe_mpi_tpu.io import save_caffemodel, save_solverstate
        solver = make_solver('type: "SGD" momentum: 0.9')
        w = rng.randn(1, 3).astype(np.float32)
        b = rng.randn(1).astype(np.float32)
        hw = rng.randn(1, 3).astype(np.float32)
        hb = rng.randn(1).astype(np.float32)
        model = str(tmp_path / "ref_iter_123.caffemodel")
        save_caffemodel(model, {"ip": [w, b]}, "lsq")
        state = str(tmp_path / "ref_iter_123.solverstate")
        save_solverstate(state, 123, model, [hw, hb])
        solver.restore(state)
        assert solver.iter == 123
        np.testing.assert_allclose(np.asarray(solver.params["ip"]["weight"]),
                                   w, rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(solver.opt_state["ip"]["weight"][0]), hw, rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(solver.opt_state["ip"]["bias"][0]), hb, rtol=1e-6)


class TestSolverDataType:
    """solver_data_type (caffe.proto:299) selects master-weight storage.
    FLOAT16 -> bf16 storage with f32 update accumulation (the step casts
    up around the update rule and optimizer history stays f32); integer
    types are rejected at net build."""

    def test_float16_storage_trains(self, rng):
        solver = make_solver('type: "SGD" momentum: 0.9\n'
                             'solver_data_type: FLOAT16')
        assert solver.params["ip"]["weight"].dtype == jnp.bfloat16
        for slots in (solver.opt_state["ip"]["weight"],
                      solver.opt_state["ip"]["bias"]):
            assert all(s.dtype == jnp.float32 for s in slots)
        feeds = lsq_feeds(rng)
        losses = [solver.step(1, lambda it: feeds) for _ in range(15)]
        assert solver.params["ip"]["weight"].dtype == jnp.bfloat16
        assert all(s.dtype == jnp.float32
                   for s in solver.opt_state["ip"]["weight"])
        assert losses[-1] < losses[0] * 0.5

    def test_float16_snapshot_roundtrip(self, rng, tmp_path):
        solver = make_solver('type: "SGD"\nsolver_data_type: FLOAT16')
        feeds = lsq_feeds(rng)
        solver.step(3, lambda it: feeds)
        w = solver.net.export_weights(solver.params, solver.net_state)
        p2, _ = solver.net.import_weights(solver.params, solver.net_state, w)
        assert p2["ip"]["weight"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(p2["ip"]["weight"], np.float32),
            np.asarray(solver.params["ip"]["weight"], np.float32))

    def test_integer_type_rejected(self):
        with pytest.raises(ValueError, match="solver_data_type"):
            make_solver("solver_data_type: INT")

    def test_double_maps_to_f32(self):
        solver = make_solver("solver_data_type: DOUBLE")
        assert solver.params["ip"]["weight"].dtype == jnp.float32
