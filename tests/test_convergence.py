"""End-to-end convergence proofs on synthetic separable data.

The reference's ground truth for these recipes is examples/mnist/
lenet_solver.prototxt and examples/cifar10/cifar10_quick_solver.prototxt
(accuracy on real MNIST/CIFAR). This environment has no dataset egress, so
the strongest runnable claim is: the full stack — LMDB data pipeline ->
transformer -> Net -> Solver with the example's own recipe — drives the
example's own topology to >=99% accuracy on a generated separable image
task. That exercises conv/pool/ip/softmax gradients, the optimizer,
LR policy, weight decay, and the evaluation path with a hard
accuracy assertion (not just "loss decreases").
"""

import os

import numpy as np
import pytest

from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from caffe_mpi_tpu.solver import Solver
from caffe_mpi_tpu.tools.cli import _build_feeders

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _make_synthetic_lmdbs(tmp_path, shape, train_n, test_n, classes=10):
    import sys
    sys.path.insert(0, _ROOT)
    from examples.common import synthetic_clusters  # THE examples' task
    from caffe_mpi_tpu.data.datasets import encode_datum
    from caffe_mpi_tpu.data.lmdb_io import write_lmdb

    paths = {}
    for split, seed, n in (("train", 0, train_n), ("test", 1, test_n)):
        imgs, labels = synthetic_clusters(n, shape, seed, classes)
        db = str(tmp_path / f"{split}_lmdb")
        write_lmdb(db, ((f"{i:08d}".encode(), encode_datum(imgs[i],
                                                           int(labels[i])))
                        for i in range(n)))
        paths[split] = db
        if split == "train":
            from caffe_mpi_tpu.io import save_blob_binaryproto
            mean = imgs.astype(np.float64).mean(axis=0).astype(np.float32)
            paths["mean"] = str(tmp_path / "mean.binaryproto")
            save_blob_binaryproto(paths["mean"], mean[None])
    return paths


def _train_example(tmp_path, solver_file, shape, max_iter, expect_acc,
                   train_n=1500, test_n=300):
    sp = SolverParameter.from_file(os.path.join(_ROOT, solver_file))
    npar = NetParameter.from_file(os.path.join(_ROOT, sp.net))
    dbs = _make_synthetic_lmdbs(tmp_path, shape, train_n, test_n)
    for l in npar.layer:
        if l.type == "Data":
            phase = l.include[0].phase if l.include else "TRAIN"
            l.data_param.source = dbs["train" if str(phase) == "TRAIN"
                                      else "test"]
        if l.transform_param and l.transform_param.mean_file:
            # point the recipe's mean file at the synthetic dataset's mean
            l.transform_param.mean_file = dbs["mean"]
    sp.net = ""
    sp.net_param = npar
    sp.max_iter = max_iter
    sp.display = 0
    sp.snapshot = 0
    sp.test_interval = 0
    sp.test_iter = [3]
    sp.snapshot_prefix = str(tmp_path / "snap")
    solver = Solver(sp)
    feed = _build_feeders(solver.net, "TRAIN")
    solver.step(max_iter, feed)

    tnet = solver.test_nets[0]
    tfeed = _build_feeders(tnet, "TEST")
    scores = solver.test_all([tfeed])
    assert scores[0]["accuracy"] >= expect_acc, scores
    return scores[0]["accuracy"]


class TestConvergence:
    def test_lenet_99pct(self, tmp_path):
        """LeNet with its own solver recipe reaches >=99% accuracy
        (reference recipe: examples/mnist/lenet_solver.prototxt)."""
        acc = _train_example(tmp_path, "examples/mnist/lenet_solver.prototxt",
                             (1, 28, 28), max_iter=250, expect_acc=0.99)
        assert acc >= 0.99

    def test_cifar10_quick_99pct(self, tmp_path):
        """cifar10_quick with its own recipe reaches >=99% accuracy
        (reference recipe: examples/cifar10/cifar10_quick_solver.prototxt)."""
        acc = _train_example(
            tmp_path, "examples/cifar10/cifar10_quick_solver.prototxt",
            (3, 32, 32), max_iter=150, expect_acc=0.99)
        assert acc >= 0.99
