"""Dtype-matrix forward parity — the analogue of the reference's
TYPED_TEST instantiation over {float, double, float16}
(test_caffe_main.hpp:34-95): key layers run in bfloat16 and must track
their f32 results within bf16 tolerance."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.core.types import DtypePolicy
from gradcheck import make_layer

BF16 = DtypePolicy(forward=jnp.bfloat16, backward=jnp.bfloat16)

CASES = [
    ('type: "Convolution" convolution_param { num_output: 4 kernel_size: 3 '
     'pad: 1 weight_filler { type: "msra" } }', [(2, 3, 8, 8)]),
    ('type: "Pooling" pooling_param { pool: MAX kernel_size: 2 stride: 2 }',
     [(2, 3, 8, 8)]),
    ('type: "Pooling" pooling_param { pool: AVE kernel_size: 3 stride: 2 pad: 1 }',
     [(2, 3, 8, 8)]),
    ('type: "LRN" lrn_param { local_size: 3 alpha: 0.1 }', [(2, 6, 4, 4)]),
    ('type: "InnerProduct" inner_product_param { num_output: 5 '
     'weight_filler { type: "xavier" } }', [(4, 12)]),
    ('type: "BatchNorm" batch_norm_param { scale_bias: true }', [(4, 3, 6, 6)]),
    ('type: "Softmax"', [(4, 7)]),
    ('type: "TanH"', [(4, 7)]),
]


@pytest.mark.parametrize("proto,shapes", CASES,
                         ids=[c[0][7:22] for c in CASES])
def test_bf16_tracks_f32(proto, shapes, rng):
    l32, params, state = make_layer(f'name: "l" {proto}', shapes)
    l16, _, _ = make_layer(f'name: "l" {proto}', shapes, policy=BF16)
    bottoms = [jnp.asarray(rng.randn(*s).astype(np.float32)) for s in shapes]
    (y32,), _ = l32.apply(params, state, bottoms, train=False, rng=None)
    (y16,), _ = l16.apply(params, state, bottoms, train=False, rng=None)
    assert y16.dtype == jnp.bfloat16
    scale = max(float(jnp.max(jnp.abs(y32))), 1e-3)
    err = float(jnp.max(jnp.abs(y16.astype(jnp.float32) - y32))) / scale
    assert err < 0.05, f"bf16 relative error {err:.3f}"
