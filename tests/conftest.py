"""Test harness: run JAX on a virtual 8-device CPU platform.

The reference tests multi-GPU data parallelism with a real in-process
P2PManager over k GPUs (test_gradient_based_solver.cpp:201-217) and leaves
multi-node untested. Here the same gap is closed portably: XLA's host
platform is split into 8 virtual devices so mesh/psum/pjit paths run as a
real 8-way SPMD program on CPU.

Platform forcing: this environment's sitecustomize registers a TPU ("axon")
PJRT plugin at interpreter startup and pins jax_platforms to it. Backends
initialize lazily, so overriding jax.config *before any jax computation*
(conftest import time) still wins. XLA_FLAGS must likewise be set before the
CPU client is created.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

assert jax.devices()[0].platform == "cpu", "tests must run on the CPU platform"
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end tests (full-size models)")


@pytest.fixture
def rng():
    return np.random.RandomState(1701)
