"""WindowData tests: window-file parsing, fg/bg sampling ratios, warping,
and end-to-end training through the layer."""

import numpy as np
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.data.window import WindowFeeder, WindowFile
from caffe_mpi_tpu.proto import LayerParameter


@pytest.fixture
def window_fixture(tmp_path, rng):
    from PIL import Image
    paths = []
    for i in range(3):
        arr = rng.randint(0, 256, (24, 24, 3)).astype(np.uint8)
        p = tmp_path / f"img{i}.png"
        Image.fromarray(arr).save(p)
        paths.append(str(p))
    lines = []
    for i, p in enumerate(paths):
        lines += [f"# {i}", p, "3 24 24", "4"]
        lines += [f"{1 + i % 2} 0.8 2 2 12 12",    # fg (overlap .8)
                  f"{1 + i % 2} 0.6 4 4 14 14",    # fg
                  "0 0.2 0 0 8 8",                  # bg
                  "0 0.1 10 10 20 20"]              # bg
    wf_path = tmp_path / "windows.txt"
    wf_path.write_text("\n".join(lines))
    return str(wf_path)


class TestWindowFile:
    def test_parse_and_classify(self, window_fixture):
        wf = WindowFile(window_fixture, fg_threshold=0.5, bg_threshold=0.5)
        assert len(wf.images) == 3
        assert len(wf.fg) == 6 and len(wf.bg) == 6
        assert all(r[2] >= 0.5 for r in wf.fg)


class TestWindowFeeder:
    def make_lp(self, source, batch=8):
        return LayerParameter.from_text(f"""
        name: "wd" type: "WindowData" top: "data" top: "label"
        window_data_param {{
          source: "{source}" batch_size: {batch} crop_size: 16
          fg_threshold: 0.5 bg_threshold: 0.5 fg_fraction: 0.25
          context_pad: 2 mirror: true
        }}
        """)

    def test_batch_shapes_and_fg_fraction(self, window_fixture):
        feeder = WindowFeeder(self.make_lp(window_fixture), "TRAIN")
        batch = feeder(0)
        assert batch["data"].shape == (8, 3, 16, 16)
        labels = batch["label"]
        assert (labels[:2] > 0).all()   # fg slots carry fg classes
        assert (labels[2:] == 0).all()  # bg slots are class 0

    def test_deterministic(self, window_fixture):
        f1 = WindowFeeder(self.make_lp(window_fixture), "TRAIN", seed=3)
        f2 = WindowFeeder(self.make_lp(window_fixture), "TRAIN", seed=3)
        np.testing.assert_array_equal(f1(5)["data"], f2(5)["data"])

    def test_trains(self, window_fixture):
        from caffe_mpi_tpu.proto import NetParameter, SolverParameter
        from caffe_mpi_tpu.solver import Solver
        net = NetParameter.from_text(f"""
        layer {{ name: "wd" type: "WindowData" top: "data" top: "label"
          window_data_param {{ source: "{window_fixture}" batch_size: 8
            crop_size: 16 fg_fraction: 0.25 }} }}
        layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "logits"
          inner_product_param {{ num_output: 3
            weight_filler {{ type: "xavier" }} }} }}
        layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
          bottom: "label" top: "loss" }}
        """)
        sp = SolverParameter.from_text(
            'base_lr: 0.0001 lr_policy: "fixed" max_iter: 5 type: "SGD"')
        sp.net_param = net
        solver = Solver(sp)
        feeder = WindowFeeder(self.make_lp(window_fixture), "TRAIN")
        loss = solver.step(5, feeder)
        assert np.isfinite(loss)
