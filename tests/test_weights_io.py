"""Weight interop tests: export/import through .caffemodel (binary wire) and
HDF5, including BatchNorm's positional-blob contract and the BVLC
variance-correction convention."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.io import (
    load_caffemodel,
    load_caffemodel_h5,
    save_caffemodel,
    save_caffemodel_h5,
)
from caffe_mpi_tpu.net import Net
from caffe_mpi_tpu.proto import NetParameter

NET = """
name: "wio"
layer { name: "in" type: "Input" top: "x"
        input_param { shape { dim: 2 dim: 3 dim: 8 dim: 8 } } }
layer { name: "conv1" type: "Convolution" bottom: "x" top: "c1"
        convolution_param { num_output: 4 kernel_size: 3 pad: 1
          weight_filler { type: "msra" } } }
layer { name: "bn1" type: "BatchNorm" bottom: "c1" top: "c1"
        batch_norm_param { scale_bias: true } }
layer { name: "relu1" type: "ReLU" bottom: "c1" top: "c1" }
layer { name: "ip" type: "InnerProduct" bottom: "c1" top: "y"
        inner_product_param { num_output: 5
          weight_filler { type: "xavier" } } }
"""


def build(seed=0):
    net = Net(NetParameter.from_text(NET), phase="TEST")
    params, state = net.init(jax.random.PRNGKey(seed))
    # non-trivial BN stats
    state["bn1"]["mean"] = jnp.asarray(np.arange(4, dtype=np.float32))
    state["bn1"]["var"] = jnp.asarray(np.arange(1, 5, dtype=np.float32))
    return net, params, state


class TestWeightRoundTrip:
    @pytest.mark.parametrize("fmt", ["binary", "h5"])
    def test_roundtrip_preserves_outputs(self, fmt, tmp_path, rng):
        net, params, state = build(seed=0)
        x = jnp.asarray(rng.randn(2, 3, 8, 8).astype(np.float32))
        blobs, _, _ = net.apply(params, state, {"x": x}, train=False)
        y_ref = np.array(blobs["y"])

        weights = net.export_weights(params, state)
        assert len(weights["bn1"]) == 5  # mean, var, correction, scale, bias
        path = str(tmp_path / f"w.caffemodel{'.h5' if fmt == 'h5' else ''}")
        if fmt == "h5":
            save_caffemodel_h5(path, weights)
            back = load_caffemodel_h5(path)
        else:
            save_caffemodel(path, weights, "wio")
            back = load_caffemodel(path)

        net2, params2, state2 = build(seed=99)  # different init
        params2, state2 = net2.import_weights(params2, state2, back)
        blobs2, _, _ = net2.apply(params2, state2, {"x": x}, train=False)
        np.testing.assert_allclose(np.array(blobs2["y"]), y_ref, rtol=1e-5,
                                   atol=1e-6)

    def test_bvlc_correction_unscaling(self):
        """BVLC-style BN blobs store mean*corr, var*corr with blobs[2]=corr;
        import must divide it out (reference batch_norm semantics)."""
        net, params, state = build()
        corr = 0.5
        weights = {
            "bn1": [np.full(4, 2.0, np.float32) * corr,      # mean * corr
                    np.full(4, 3.0, np.float32) * corr,      # var * corr
                    np.asarray([corr], np.float32),
                    np.ones(4, np.float32), np.zeros(4, np.float32)],
        }
        params2, state2 = net.import_weights(params, state, weights)
        np.testing.assert_allclose(np.array(state2["bn1"]["mean"]), 2.0)
        np.testing.assert_allclose(np.array(state2["bn1"]["var"]), 3.0)

    def test_zero_correction_zeroes_stats(self):
        """blobs[2] == 0 (never-trained BVLC model) means scale_factor = 0:
        the stored mean/var garbage is ZEROED on import, not kept
        (batch_norm_layer.cpp scale_factor = blobs[2]==0 ? 0 : 1/blobs[2])."""
        net, params, state = build()
        weights = {
            "bn1": [np.full(4, 7.5, np.float32),   # garbage accumulators
                    np.full(4, -3.0, np.float32),
                    np.zeros(1, np.float32),       # zero correction
                    np.ones(4, np.float32), np.zeros(4, np.float32)],
        }
        _, state2 = net.import_weights(params, state, weights)
        np.testing.assert_array_equal(np.array(state2["bn1"]["mean"]), 0.0)
        np.testing.assert_array_equal(np.array(state2["bn1"]["var"]), 0.0)

    def test_unmatched_layers_keep_init(self):
        net, params, state = build()
        w0 = np.array(params["conv1"]["weight"])
        params2, _ = net.import_weights(params, state, {"ip": [
            np.ones((5, 256), np.float32), np.zeros(5, np.float32)]})
        np.testing.assert_array_equal(np.array(params2["conv1"]["weight"]), w0)
        np.testing.assert_array_equal(np.array(params2["ip"]["weight"]), 1.0)

    def test_h5_roundtrip_with_slash_layer_names(self, tmp_path):
        """GoogLeNet-style names (inception_3a/1x1) nest as HDF5 groups;
        the loader must walk to the leaf groups and rebuild the names
        (the reference resolves them by name, net.cpp ToHDF5/CopyFrom)."""
        w = {"inception_3a/1x1": [np.ones((4, 2), np.float32),
                                  np.arange(4, dtype=np.float32)],
             "inception_3a/pool_proj": [np.full((2, 2), 3.0, np.float32)],
             "conv1/7x7_s2": [np.zeros((2, 3), np.float32)],
             "plain": [np.ones(3, np.float32)]}
        p = str(tmp_path / "w.caffemodel.h5")
        save_caffemodel_h5(p, w)
        back = load_caffemodel_h5(p)
        assert sorted(back) == sorted(w)
        for k in w:
            assert len(back[k]) == len(w[k])
            for a, b in zip(back[k], w[k]):
                np.testing.assert_array_equal(a, b)

    def test_v0_binary_caffemodel_blobs(self):
        """V0-era .caffemodel: weights nested as layers{layer{name=1,
        blobs=50}} (caffe.proto:1473,1515). Hand-encode the wire bytes and
        parse them."""
        from caffe_mpi_tpu.io import _tag, _varint, encode_blob, \
            parse_caffemodel
        w = np.arange(6, dtype=np.float32).reshape(2, 3)
        blob = encode_blob(w)
        v0 = (_tag(1, 2) + _varint(len(b"ipw")) + b"ipw"
              + _tag(50, 2) + _varint(len(blob)) + blob)
        v1 = _tag(1, 2) + _varint(len(v0)) + v0
        buf = _tag(2, 2) + _varint(len(v1)) + v1
        out = parse_caffemodel(bytes(buf))
        assert list(out) == ["ipw"]
        np.testing.assert_array_equal(out["ipw"][0], w)

    def test_shape_mismatch_raises(self):
        net, params, state = build()
        with pytest.raises(ValueError, match="incompatible"):
            net.import_weights(params, state,
                               {"ip": [np.ones((7, 99), np.float32)]})
