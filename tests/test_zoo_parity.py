"""Model-zoo parity tests: generated nets must match the reference zoo's
weight-bearing layers BY NAME and output-channel count (so reference
.caffemodel files load layer-for-layer). Skipped when the reference tree
is not mounted."""

import os

import pytest

from caffe_mpi_tpu.proto import NetParameter, normalize_net

REF = "/root/reference/models"

CASES = [
    ("googlenet", f"{REF}/bvlc_googlenet/train_val.prototxt"),
    ("inception_v3", f"{REF}/inception_v3/train_val.prototxt"),
    ("resnet50", f"{REF}/resnet50/train_val.prototxt"),
    ("resnet18", f"{REF}/resnet18/train_val.prototxt"),
    ("alexnet", f"{REF}/bvlc_alexnet/train_val.prototxt"),
    ("caffenet", f"{REF}/bvlc_reference_caffenet/train_val.prototxt"),
    ("vgg16", f"{REF}/vgg16/train_val.prototxt"),
    ("alexnet_owt", f"{REF}/alexnet_owt/train_val.prototxt"),
    ("inception_v2", f"{REF}/inception_v2/train_val.prototxt"),
    ("alexnet_bn", f"{REF}/alexnet_bn/train_val.prototxt"),
    ("cifar10_nv", f"{REF}/cifar10_nv/cifar10_nv_train_test.prototxt"),
    ("finetune_flickr_style",
     f"{REF}/finetune_flickr_style/train_val.prototxt"),
]


def weight_layers(net):
    out = {}
    for lp in net.layer:
        if lp.type == "Convolution":
            out[lp.name] = ("conv", lp.convolution_param.num_output)
        elif lp.type == "InnerProduct":
            out[lp.name] = ("ip", lp.inner_product_param.num_output)
    return out


@pytest.mark.parametrize("ours,ref_path", CASES, ids=[c[0] for c in CASES])
def test_weight_layer_parity(ours, ref_path):
    if not os.path.exists(ref_path):
        pytest.skip("reference not mounted")
    our_net = normalize_net(
        NetParameter.from_file(f"models/{ours}/train_val.prototxt"))
    ref_net = normalize_net(NetParameter.from_file(ref_path))
    ours_w = weight_layers(our_net)
    ref_w = weight_layers(ref_net)
    missing = set(ref_w) - set(ours_w)
    extra = set(ours_w) - set(ref_w)
    changed = {k: (ref_w[k], ours_w[k])
               for k in set(ref_w) & set(ours_w) if ref_w[k] != ours_w[k]}
    assert not missing, f"missing weight layers: {sorted(missing)[:10]}"
    assert not extra, f"extra weight layers: {sorted(extra)[:10]}"
    assert not changed, f"channel mismatches: {changed}"


def test_aux_heads_weighted():
    net = normalize_net(
        NetParameter.from_file("models/googlenet/train_val.prototxt"))
    aux = [l for l in net.layer if l.type == "SoftmaxWithLoss"
           and l.loss_weight == [0.3]]
    assert len(aux) == 2
