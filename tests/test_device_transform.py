"""Device-side (in-graph) data transform — parity with the host
DataTransformer and the raw-uint8 feed contract.

Mirrors the reference's GPU-transform coverage (data_transformer.cu is
exercised against the CPU path via use_gpu_transform in
test_data_layer.cpp): the jitted crop/mean/mirror/scale must agree with
the host transform bit-for-bit, because both consume the same per-record
Philox decision streams.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.data.device_transform import (
    aug_key, compute_aug, device_transform, wants_device_transform)
from caffe_mpi_tpu.data.transformer import DataTransformer
from caffe_mpi_tpu.proto.config import (LayerParameter,
                                        TransformationParameter)


def host_batch(tf, imgs, flats):
    return np.stack([tf(img, rng=tf.record_rng(f))
                     for img, f in zip(imgs, flats)])


def device_batch(tf, imgs, flats, crop, scale):
    raw = jnp.asarray(np.stack(imgs))
    aug = compute_aug(tf, flats, imgs[0].shape[-2:], len(imgs))
    fn = jax.jit(lambda r, a: device_transform(
        r, a, crop=crop, mean=tf.mean, scale=scale))
    return np.asarray(fn(raw, jnp.asarray(aug)))


class TestParityWithHost:
    def _imgs(self, n=8, c=3, h=12, w=10, seed=0):
        r = np.random.RandomState(seed)
        return [r.randint(0, 256, (c, h, w)).astype(np.uint8)
                for _ in range(n)], list(range(100, 100 + n))

    @pytest.mark.parametrize("phase", ["TRAIN", "TEST"])
    def test_crop_mirror_meanvalue_scale(self, phase):
        imgs, flats = self._imgs()
        tp = TransformationParameter(
            scale=0.017, mirror=True, crop_size=8,
            mean_value=[104.0, 117.0, 123.0], random_seed=7)
        tf = DataTransformer(tp, phase)
        np.testing.assert_array_equal(
            device_batch(tf, imgs, flats, crop=8, scale=0.017),
            host_batch(tf, imgs, flats))

    def test_fullsize_mean_file_cropped_at_window(self, tmp_path):
        """A full-size mean is subtracted at the same (unmirrored) crop
        window the image was cropped at (data_transformer.cpp)."""
        from caffe_mpi_tpu.io import save_blob_binaryproto
        imgs, flats = self._imgs(c=1, h=9, w=9, seed=3)
        mean = np.random.RandomState(9).rand(1, 9, 9).astype(np.float32) * 50
        save_blob_binaryproto(str(tmp_path / "mean.binaryproto"), mean)
        tp = TransformationParameter(mirror=True, crop_size=5,
                                     mean_file="mean.binaryproto",
                                     random_seed=1)
        tf = DataTransformer(tp, "TRAIN", model_dir=str(tmp_path))
        np.testing.assert_array_equal(
            device_batch(tf, imgs, flats, crop=5, scale=1.0),
            host_batch(tf, imgs, flats))

    def test_no_crop_mirror_only(self):
        imgs, flats = self._imgs(h=6, w=6, seed=5)
        tp = TransformationParameter(mirror=True, random_seed=11)
        tf = DataTransformer(tp, "TRAIN")
        np.testing.assert_array_equal(
            device_batch(tf, imgs, flats, crop=0, scale=1.0),
            host_batch(tf, imgs, flats))

    def test_train_draws_vary_per_record(self):
        imgs, flats = self._imgs(n=64, h=16, w=16)
        tp = TransformationParameter(mirror=True, crop_size=8, random_seed=2)
        aug = compute_aug(DataTransformer(tp, "TRAIN"), flats, (16, 16), 64)
        assert len(np.unique(aug[:, 0])) > 1   # crop offsets vary
        assert 0 < aug[:, 2].sum() < 64        # some mirrored, not all


class TestPredicate:
    def _lp(self, **tp_fields):
        lp = LayerParameter(name="d", type="Data")
        lp.transform_param = TransformationParameter(**tp_fields)
        return lp

    def test_default_on(self):
        assert wants_device_transform(self._lp(crop_size=4, mirror=True))

    def test_explicit_opt_out(self):
        lp = self._lp()
        lp.transform_param = TransformationParameter.from_text(
            "use_gpu_transform: false")
        assert not wants_device_transform(lp)

    def test_force_color_is_host_only(self):
        assert not wants_device_transform(self._lp(force_color=True))


class TestEndToEnd:
    def _make_db(self, tmp_path, n=32, shape=(1, 8, 8)):
        from caffe_mpi_tpu.data.datasets import encode_datum
        from caffe_mpi_tpu.data.lmdb_io import write_lmdb
        r = np.random.RandomState(0)
        imgs = r.randint(0, 256, (n, *shape)).astype(np.uint8)
        labels = r.randint(0, 2, n)
        db = str(tmp_path / "db_lmdb")
        write_lmdb(db, [(f"{i:08d}".encode(),
                         encode_datum(imgs[i], int(labels[i])))
                        for i in range(n)])
        return db, imgs, labels

    NET = """
    name: "devtf"
    layer {{ name: "data" type: "Data" top: "data" top: "label"
            data_param {{ source: "{db}" backend: LMDB batch_size: 8 }}
            transform_param {{ crop_size: 6 mirror: true scale: 0.0078125
                              mean_value: 128 random_seed: 3 }} }}
    layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "y"
            inner_product_param {{ num_output: 2
              weight_filler {{ type: "xavier" }} }} }}
    layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "label"
            top: "l" }}
    """

    def test_net_contract_and_host_parity(self, tmp_path):
        """The net exposes the raw+aug feed contract; applying it equals
        applying the HOST-transformed batch through an opted-out net."""
        from caffe_mpi_tpu.data.feeder import feeder_from_layer
        from caffe_mpi_tpu.net import Net
        from caffe_mpi_tpu.proto import NetParameter

        db, imgs, labels = self._make_db(tmp_path)
        net = Net(NetParameter.from_text(self.NET.format(db=db)),
                  phase="TRAIN")
        dlayer = net.layers[0]
        assert dlayer.dev_transform
        assert net.feed_specs["data"] == ((8, 1, 8, 8), "uint8")
        assert net.feed_specs[aug_key("data")] == ((8, 3), "aug")
        assert net.blob_shapes["data"] == (8, 1, 6, 6)

        feeder = feeder_from_layer(dlayer.lp, "TRAIN",
                                   device_transform=True)
        feeds = feeder(0)
        assert feeds["data"].dtype == np.uint8
        params, state = net.init(jax.random.PRNGKey(0))
        env, _, loss = net.apply(params, state,
                                 {k: jnp.asarray(v)
                                  for k, v in feeds.items()},
                                 train=True, rng=jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))

        # host-path reference: same records through the host transformer
        net_host = Net(NetParameter.from_text(self.NET.format(db=db)),
                       phase="TRAIN", device_transform=False)
        assert not net_host.layers[0].dev_transform
        feeder_h = feeder_from_layer(dlayer.lp, "TRAIN",
                                     device_transform=False)
        # the device path shares RNG streams with the PYTHON host path;
        # the native C++ path draws from splitmix64 by design
        # (native/transform.cc:12-15) — force python for exact parity
        feeder_h._native = False
        feeds_h = feeder_h(0)
        env_h, _, loss_h = net_host.apply(
            params, state, {k: jnp.asarray(v) for k, v in feeds_h.items()},
            train=True, rng=jax.random.PRNGKey(1))
        np.testing.assert_array_equal(np.asarray(env["data"]),
                                      np.asarray(env_h["data"]))
        np.testing.assert_allclose(float(loss), float(loss_h), rtol=1e-6)
        feeder.close()
        feeder_h.close()

    def test_solver_trains_with_device_transform(self, tmp_path):
        from caffe_mpi_tpu.data.feeder import data_shape_probe
        from caffe_mpi_tpu.proto import SolverParameter
        from caffe_mpi_tpu.solver import Solver
        from caffe_mpi_tpu.tools.cli import _build_feeders

        db, _, _ = self._make_db(tmp_path)
        (tmp_path / "net.prototxt").write_text(self.NET.format(db=db))
        sp = SolverParameter.from_text(
            f'net: "{tmp_path}/net.prototxt"\nbase_lr: 0.1\n'
            'lr_policy: "fixed"\nmax_iter: 40\ndisplay: 0\n')
        solver = Solver(sp)
        assert solver.net.layers[0].dev_transform
        feeder = _build_feeders(solver.net, "TRAIN")
        assert feeder.device_transform
        # convergence on a small memorizable set, deflaked: per-step
        # losses oscillate epoch-to-epoch (8-record batches over 32
        # records with aggressive augmentation), so compare EPOCH-scale
        # averages instead of two individual steps — descent is the
        # claim, not monotonicity
        losses = [solver.step(1, feeder) for _ in range(sp.max_iter)]
        assert np.all(np.isfinite(losses))
        assert np.mean(losses[-8:]) < np.mean(losses[:8])
        feeder.close()

    def test_mixed_size_records_fall_back_to_host(self, tmp_path):
        """convert_imageset-without-resize layouts store records of mixed
        sizes; crop normalizes them on the host path. The probe samples
        across the DB and must keep such layers on the host path."""
        from caffe_mpi_tpu.data.datasets import encode_datum
        from caffe_mpi_tpu.data.lmdb_io import write_lmdb
        from caffe_mpi_tpu.net import Net
        from caffe_mpi_tpu.proto import NetParameter
        r = np.random.RandomState(0)
        recs = []
        for i in range(10):
            h, w = (8, 8) if i < 9 else (10, 9)   # one odd record at the end
            recs.append((f"{i:04d}".encode(),
                         encode_datum(r.randint(0, 256, (1, h, w))
                                      .astype(np.uint8), 0)))
        db = str(tmp_path / "mixed_lmdb")
        write_lmdb(db, recs)
        net = Net(NetParameter.from_text(f"""
            layer {{ name: "d" type: "Data" top: "data" top: "label"
                    data_param {{ source: "{db}" backend: LMDB
                                  batch_size: 2 }}
                    transform_param {{ crop_size: 6 }} }}
            """), phase="TRAIN")
        assert not net.layers[0].dev_transform
        assert net.feed_specs["data"] == ((2, 1, 6, 6), "float")

    def test_float_records_fall_back_to_host(self, tmp_path):
        """Non-uint8 datums cannot stage raw; the probe reports no raw
        shape and the layer stays on the host path."""
        from caffe_mpi_tpu.data.datasets import encode_datum_float
        from caffe_mpi_tpu.data.lmdb_io import write_lmdb
        from caffe_mpi_tpu.net import Net
        from caffe_mpi_tpu.proto import NetParameter
        r = np.random.RandomState(0)
        db = str(tmp_path / "f_lmdb")
        write_lmdb(db, [(f"{i:04d}".encode(),
                         encode_datum_float(
                             r.rand(1, 4, 4).astype(np.float32), 0))
                        for i in range(4)])
        net = Net(NetParameter.from_text(f"""
            layer {{ name: "d" type: "Data" top: "data" top: "label"
                    data_param {{ source: "{db}" backend: LMDB
                                  batch_size: 2 }} }}
            """), phase="TRAIN")
        assert not net.layers[0].dev_transform
        assert net.feed_specs["data"] == ((2, 1, 4, 4), "float")
