"""Self-healing training (ISSUE 4): on-device non-finite guard,
divergence rewind, and the data-integrity plane.

The acceptance bars:
- guard OFF (default): nothing changes — covered implicitly by every
  pre-existing solver test;
- guard ON, clean data: training is BITWISE identical to guard-off on
  CPU, for step_chunk 1 and K (the guard lives in a lax.cond branch so
  the update graph compiles to identical arithmetic — see
  solver._iteration_fn);
- injected NaNs: the bad step is skipped on device (params/momentum
  unchanged), M consecutive skips exit 88, and the supervised rewind
  resumes iteration-exact vs an uninterrupted clean run;
- corrupt records: crc32c is verified on the DB read path, corrupt
  records quarantine with a journal, and a replay makes identical
  substitution decisions (same final weight bits).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from caffe_mpi_tpu.proto import SolverParameter
from caffe_mpi_tpu.proto.config import NetParameter
from caffe_mpi_tpu.solver import Solver
from caffe_mpi_tpu.utils import resilience

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LSQ_NET = """
name: "lsq"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 8 dim: 3 } shape { dim: 8 dim: 1 } } }
layer { name: "ip" type: "InnerProduct" bottom: "x" top: "pred"
        inner_product_param { num_output: 1
          weight_filler { type: "gaussian" std: 1 } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "pred" bottom: "t" top: "l" }
"""


def make_solver(extra="", mesh=None):
    sp = SolverParameter.from_text(
        f'base_lr: 0.1 max_iter: 1000 lr_policy: "fixed" display: 0 '
        f'momentum: 0.9 random_seed: 3\n{extra}')
    sp.net_param = NetParameter.from_text(LSQ_NET)
    return Solver(sp, mesh=mesh)


def lsq_data(n=32):
    r = np.random.RandomState(1)
    out = []
    for _ in range(n):
        x = r.randn(8, 3).astype(np.float32)
        t = (x @ np.array([[1.0], [-2.0], [0.5]]) + 0.3).astype(np.float32)
        out.append({"x": x, "t": t})
    return out


def assert_bitwise_state(a: Solver, b: Solver):
    for ln in a.params:
        for pn in a.params[ln]:
            assert np.array_equal(np.asarray(a.params[ln][pn]),
                                  np.asarray(b.params[ln][pn])), \
                f"params {ln}/{pn} differ"
    for ln in a.opt_state:
        for pn in a.opt_state[ln]:
            for si, (sa, sb) in enumerate(zip(a.opt_state[ln][pn],
                                              b.opt_state[ln][pn])):
                assert np.array_equal(np.asarray(sa), np.asarray(sb)), \
                    f"opt {ln}/{pn}[{si}] differ"


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    resilience.FAULTS.configure("")


# ---------------------------------------------------------------------------
# guard-on == guard-off, bitwise, clean data
# ---------------------------------------------------------------------------

class TestGuardEquivalence:
    @pytest.mark.parametrize("chunk", [1, 5])
    def test_bitwise_equal_clean_data(self, chunk):
        data = lsq_data()
        feed = lambda it: data[it % 32]
        a = make_solver(f"step_chunk: {chunk}")
        b = make_solver(f"step_chunk: {chunk} train_guard: true")
        a.step(33, feed)
        b.step(33, feed)
        assert_bitwise_state(a, b)
        assert b.skipped_steps == 0
        # zero extra dispatches: the guard rides inside the programs
        assert b.dispatch_count == a.dispatch_count
        assert b.guard_sync_count > 0

    def test_bitwise_equal_under_mesh(self):
        from caffe_mpi_tpu.parallel import MeshPlan
        data = lsq_data()
        feed = lambda it: data[it % 32]
        a = make_solver("step_chunk: 4", mesh=MeshPlan.data_parallel())
        b = make_solver("step_chunk: 4 train_guard: true",
                        mesh=MeshPlan.data_parallel())
        a.step(9, feed)
        b.step(9, feed)
        assert_bitwise_state(a, b)
        assert b.skipped_steps == 0

    def test_guard_rejects_gpipe(self):
        with pytest.raises(ValueError, match="train_guard.*gpipe"):
            sp = SolverParameter.from_text(
                'base_lr: 0.1 max_iter: 10 lr_policy: "fixed" '
                'train_guard: true')
            sp.net_param = NetParameter.from_text(LSQ_NET)
            Solver(sp, gpipe={"stages": 1, "micro": 1})


# ---------------------------------------------------------------------------
# skip-step semantics + divergence policy (in-process)
# ---------------------------------------------------------------------------

class TestSkipStep:
    def test_nan_step_skipped_params_unchanged(self):
        data = lsq_data()
        feed = lambda it: data[it % 32]
        resilience.FAULTS.configure("nan_grad:1:0:5")
        s = make_solver("train_guard: true guard_max_skips: 0")
        s.step(5, feed)
        w5 = np.asarray(s.params["ip"]["weight"]).copy()
        h5 = np.asarray(s.opt_state["ip"]["weight"][0]).copy()
        s.step(1, feed)  # iteration 5: poisoned -> skipped on device
        assert s.skipped_steps == 1
        assert np.array_equal(np.asarray(s.params["ip"]["weight"]), w5)
        assert np.array_equal(np.asarray(s.opt_state["ip"]["weight"][0]),
                              h5)
        # training continues and the consecutive counter resets
        s.step(4, feed)
        assert s.skipped_steps == 1

    def test_skip_then_recover_matches_freeze(self, tmp_path):
        """A skipped iteration is a no-op: the guarded run equals a
        run that never saw the bad iteration's update (same params
        before and after the skip)."""
        data = lsq_data()
        feed = lambda it: data[it % 32]
        resilience.FAULTS.configure("nan_grad:1:0:3")
        g = make_solver("train_guard: true step_chunk: 4")
        g.step(4, feed)  # iterations 0..3; 3 skipped inside the chunk
        assert g.skipped_steps == 1
        resilience.FAULTS.configure("")
        clean = make_solver("train_guard: true")
        clean.step(3, feed)  # clean run stopped before the bad iter
        assert_bitwise_state(g, clean)

    def test_consecutive_skips_raise_numeric_anomaly(self, tmp_path):
        data = lsq_data()
        feed = lambda it: data[it % 32]
        resilience.FAULTS.configure("nan_grad:3:0:2")
        s = make_solver("train_guard: true guard_max_skips: 3")
        s.sp.snapshot_prefix = str(tmp_path / "s")
        with pytest.raises(resilience.NumericAnomalyError) as ei:
            s.step(10, feed)
        assert ei.value.consec == 3
        run = resilience.read_run_manifest(str(tmp_path / "s"))
        assert run["reason"] == "numeric_anomaly"
        assert run["consec_skips"] == 3
        assert run["exit_code"] == resilience.EXIT_NUMERIC == 88

    def test_mid_chunk_burst_still_trips_policy(self, tmp_path):
        """A >=M consecutive burst that RECOVERS before the chunk
        boundary must still exit 88: `consec` has reset by the time the
        host looks, so the carry also tracks the longest burst seen
        (max_consec, monotone over the run — sound because reaching M
        always exits)."""
        data = lsq_data()
        feed = lambda it: data[it % 32]
        resilience.FAULTS.configure("nan_grad:3:0:2")  # iters 2,3,4 bad
        s = make_solver("train_guard: true guard_max_skips: 3 "
                        "step_chunk: 10")
        s.sp.snapshot_prefix = str(tmp_path / "s")
        with pytest.raises(resilience.NumericAnomalyError) as ei:
            s.step(10, feed)  # one chunk; burst ends at iter 5
        assert ei.value.consec == 3

    def test_divergence_blocks_snapshot_at_its_boundary(self, tmp_path):
        """A burst reaching M just before a snapshot boundary must
        raise BEFORE that snapshot is written: the deferred check is
        drained ahead of snapshot(), otherwise the rewind target would
        seal the skipped iterations and recovery would not be
        iteration-exact (bad iters 6,7 with snapshot 4: the iter-8
        snapshot must not exist)."""
        from caffe_mpi_tpu.utils.resilience import iter_snapshot_manifests
        data = lsq_data()
        feed = lambda it: data[it % 32]
        resilience.FAULTS.configure("nan_grad:2:0:6")
        s = make_solver("train_guard: true guard_max_skips: 2 "
                        "snapshot: 4")
        s.sp.snapshot_prefix = str(tmp_path / "s")
        with pytest.raises(resilience.NumericAnomalyError):
            s.step(12, feed)
        s.close()
        its = [it for it, _ in iter_snapshot_manifests(str(tmp_path / "s"))]
        assert its == [4], its  # iter-8 snapshot was NOT written

    def test_loss_spike_detector(self):
        data = lsq_data()
        feed = lambda it: data[it % 32]
        resilience.FAULTS.configure("loss_spike:1:0:6")
        s = make_solver("train_guard: true guard_loss_spike: 3.0 "
                        "guard_max_skips: 0")
        s.step(10, feed)
        assert s.skipped_steps == 1  # finite but 1e6x the EMA: skipped


# ---------------------------------------------------------------------------
# data-integrity plane (in-process units)
# ---------------------------------------------------------------------------

def _write_datum_lmdb(path, n=16, shape=(1, 6, 6)):
    from caffe_mpi_tpu.data.datasets import encode_datum
    from caffe_mpi_tpu.data.lmdb_io import write_lmdb
    r = np.random.RandomState(7)
    write_lmdb(path, ((f"{i:08d}".encode(),
                       encode_datum(r.randint(0, 256, shape)
                                    .astype(np.uint8), int(i % 4)))
                      for i in range(n)))
    return path


class TestDataIntegrity:
    def test_lmdb_sidecar_written_and_verified(self, tmp_path):
        from caffe_mpi_tpu.data.datasets import LMDBDataset
        from caffe_mpi_tpu.data.lmdb_io import read_crc_sidecar
        db = _write_datum_lmdb(str(tmp_path / "db"))
        assert os.path.exists(tmp_path / "db" / "data.mdb.crc32c")
        crcs = read_crc_sidecar(db)
        assert crcs is not None and len(crcs) == 16
        ds = LMDBDataset(db)
        assert ds._crcs is not None
        img, label = ds.get(3)
        assert img.shape == (1, 6, 6) and label == 3

    def test_on_disk_bitrot_detected(self, tmp_path):
        """Real bitrot: flip one byte of record 5's value bytes inside
        data.mdb — only that record must fail, with a crc mismatch."""
        from caffe_mpi_tpu.data.datasets import LMDBDataset
        db = _write_datum_lmdb(str(tmp_path / "db"))
        ds = LMDBDataset(db)
        # locate the record's unique value bytes in the data file
        from caffe_mpi_tpu.data.lmdb_io import LMDBReader
        rd = LMDBReader(db)
        val = rd.get(ds.keys[5])
        rd.close()
        data_path = os.path.join(db, "data.mdb")
        blob = bytearray(open(data_path, "rb").read())
        at = bytes(blob).find(val)
        assert at > 0
        blob[at + len(val) // 2] ^= 0xFF
        open(data_path, "wb").write(bytes(blob))
        ds2 = LMDBDataset(db)
        with pytest.raises(resilience.RecordIntegrityError,
                           match="crc32c mismatch"):
            ds2.get(5)
        ds2.get(4)  # neighbors unaffected
        ds2.get(6)

    def test_rotten_sidecar_is_ignored_not_fatal(self, tmp_path):
        from caffe_mpi_tpu.data.datasets import LMDBDataset
        db = _write_datum_lmdb(str(tmp_path / "db"))
        side = os.path.join(db, "data.mdb.crc32c")
        blob = bytearray(open(side, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(side, "wb").write(bytes(blob))
        ds = LMDBDataset(db)  # warns, loads unverified
        assert ds._crcs is None
        ds.get(5)

    def test_leveldb_block_crc_verified(self, tmp_path):
        from caffe_mpi_tpu.data.datasets import LevelDBDataset, \
            encode_datum
        from caffe_mpi_tpu.data.leveldb_io import (LevelDBError,
                                                   write_leveldb)
        r = np.random.RandomState(7)
        items = [(f"{i:08d}".encode(),
                  encode_datum(r.randint(0, 256, (1, 6, 6))
                               .astype(np.uint8), i % 4))
                 for i in range(16)]
        db = str(tmp_path / "ldb")
        write_leveldb(db, items)
        ds = LevelDBDataset(db)
        ds.get(3)
        # flip a byte inside the first data block: the reader's
        # open-time index build re-reads every block, so format-level
        # rot is a hard, named failure at open
        p = os.path.join(db, "000005.ldb")
        blob = bytearray(open(p, "rb").read())
        blob[50] ^= 0xFF
        open(p, "wb").write(bytes(blob))
        with pytest.raises(LevelDBError, match="crc32c"):
            LevelDBDataset(db)

    def test_feeder_quarantines_deterministically(self, tmp_path):
        from caffe_mpi_tpu.data.datasets import LMDBDataset
        from caffe_mpi_tpu.data.feeder import Feeder
        db = _write_datum_lmdb(str(tmp_path / "db"))
        resilience.FAULTS.configure("record_corrupt:1:0:5")
        resilience.QUARANTINE.configure(str(tmp_path / "q.json"))
        try:
            ds = LMDBDataset(db)
            f = Feeder(ds, None, 4, threads=1)
            batch1 = f._build_batch_inner(1)  # records 4..7: 5 is rot
            batch2 = f._build_batch_inner(1)  # replay: same decision
            np.testing.assert_array_equal(batch1["data"], batch2["data"])
            # the substitute is the next healthy record by index
            img6, _ = ds.get(6)
            np.testing.assert_array_equal(
                np.asarray(batch1["data"][1]), img6.astype(np.float32))
            doc = json.load(open(tmp_path / "q.json"))
            assert [e["index"] for e in doc["records"]] == [5]
            assert doc["records"][0]["substitute"] == 6
            f.close()
        finally:
            resilience.QUARANTINE.configure(None)

    def test_record_decode_quarantines_without_sidecar(self, tmp_path):
        """Truncated record on a sidecar-less (reference-written) DB:
        no crc to compare, but the Datum parse fails and quarantines
        the same way."""
        from caffe_mpi_tpu.data.datasets import LMDBDataset, encode_datum
        from caffe_mpi_tpu.data.lmdb_io import write_lmdb
        r = np.random.RandomState(7)
        db = str(tmp_path / "db")
        write_lmdb(db, ((f"{i:08d}".encode(),
                         encode_datum(r.randint(0, 256, (1, 6, 6))
                                      .astype(np.uint8), i % 4))
                        for i in range(16)), integrity=False)
        resilience.FAULTS.configure("record_decode:1:0:5")
        ds = LMDBDataset(db)
        assert ds._crcs is None
        with pytest.raises(resilience.RecordIntegrityError,
                           match="undecodable Datum"):
            ds.get(5)
        ds.get(4)

    def test_systematic_corruption_is_hard_failure(self, tmp_path):
        from caffe_mpi_tpu.data.datasets import LMDBDataset
        from caffe_mpi_tpu.data.feeder import Feeder
        db = _write_datum_lmdb(str(tmp_path / "db"))
        # every record rotten: the probe window exhausts -> named error
        resilience.FAULTS.configure("record_corrupt:16:0:0")
        ds = LMDBDataset(db)
        f = Feeder(ds, None, 4, threads=1)
        with pytest.raises(resilience.DataIntegrityError,
                           match="systematic"):
            f._build_batch_inner(0)
        f.close()


# ---------------------------------------------------------------------------
# supervisor anomaly routing (tiny shell children, no jax)
# ---------------------------------------------------------------------------

class TestSuperviseAnomalyRouting:
    def _mk_child(self, tmp_path):
        """Exits 88 on the first run, 0 once '-lr_scale' is passed."""
        script = tmp_path / "child.sh"
        script.write_text(
            '#!/bin/sh\nfor a in "$@"; do\n'
            '  [ "$a" = "-lr_scale" ] && exit 0\ndone\nexit 88\n')
        script.chmod(0o755)
        return str(script)

    def test_rewind_lr_appends_lr_scale(self, tmp_path):
        child = self._mk_child(tmp_path)
        rc = resilience.supervise(
            [child], [child, "-resume", "auto"], 2,
            failure_log=str(tmp_path / "f.log"),
            anomaly_action="rewind_lr", anomaly_lr_mult=0.1,
            backoff_base=0.01)
        assert rc == 0  # restart carried -lr_scale -> child succeeded
        assert "numeric divergence" in (tmp_path / "f.log").read_text()

    def test_plain_rewind_never_scales_lr(self, tmp_path):
        child = self._mk_child(tmp_path)
        rc = resilience.supervise(
            [child], [child, "-resume", "auto"], 1,
            failure_log=str(tmp_path / "f.log"),
            anomaly_action="rewind", backoff_base=0.01)
        # without -lr_scale the child keeps exiting 88: crash-loop guard
        assert rc == resilience.EXIT_NUMERIC


# ---------------------------------------------------------------------------
# e2e acceptance: CLI subprocesses
# ---------------------------------------------------------------------------

def _build_workspace(root):
    from caffe_mpi_tpu.data.datasets import encode_datum
    from caffe_mpi_tpu.data.lmdb_io import write_lmdb
    os.makedirs(root, exist_ok=True)
    db = os.path.join(root, "train_lmdb")
    r = np.random.RandomState(7)
    write_lmdb(db, ((f"{i:08d}".encode(),
                     encode_datum(r.randint(0, 256, (1, 6, 6), np.uint8)
                                  .astype(np.uint8), int(i % 4)))
                    for i in range(16)))
    net = os.path.join(root, "net.prototxt")
    # use_gpu_transform: false => float host-transform feeds, which the
    # nan_grad/loss_spike sites can poison (the uint8 device-transform
    # staging path has no float leaf to NaN)
    with open(net, "w") as f:
        f.write(f"""
name: "sgnet"
layer {{ name: "data" type: "Data" top: "data" top: "label"
        transform_param {{ use_gpu_transform: false }}
        data_param {{ source: "{db}" batch_size: 4 backend: LMDB }} }}
layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "score"
        inner_product_param {{ num_output: 4
          weight_filler {{ type: "xavier" }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "score"
        bottom: "label" top: "loss" }}
""")
    solver = os.path.join(root, "solver.prototxt")
    with open(solver, "w") as f:
        f.write(f'net: "{net}"\nbase_lr: 0.05 momentum: 0.9\n'
                f'lr_policy: "fixed" max_iter: 12 random_seed: 3\n'
                f'display: 0 snapshot: 4\n')
    return solver


def _run_cli(solver, prefix, *extra, faults="", faults_dir="",
             timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=_ROOT, CAFFE_TPU_FAULTS=faults,
               CAFFE_TPU_FAULTS_DIR=faults_dir)
    env.pop("CAFFE_SUPERVISED_CHILD", None)
    cmd = [sys.executable, "-m", "caffe_mpi_tpu.tools.cli", "train",
           "-solver", solver, "-snapshot_prefix", prefix, *extra]
    return subprocess.run(cmd, env=env, cwd=_ROOT, timeout=timeout,
                          capture_output=True, text=True)


def _final_weights(prefix):
    from caffe_mpi_tpu.io import load_caffemodel
    path = f"{prefix}_iter_12.caffemodel"
    assert os.path.exists(path), f"missing final snapshot {path}"
    return load_caffemodel(path)


def _assert_bitwise_equal(got, want):
    assert set(got) == set(want)
    for lname in want:
        for a, b in zip(got[lname], want[lname]):
            assert np.array_equal(a, b), f"{lname}: weight bits differ"


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("train_guard"))
    solver = _build_workspace(root)
    prefix = os.path.join(root, "baseline", "s")
    r = _run_cli(solver, prefix, "-train_guard")
    assert r.returncode == 0, r.stderr[-2000:]
    return {"root": root, "solver": solver,
            "baseline": _final_weights(prefix)}


class TestEndToEndSelfHealing:
    def test_nan_divergence_exit88_supervised_rewind(self, ws):
        """Iterations 5-6 NaN-poisoned, guard_max_skips 2: the child
        journals the anomaly and exits 88 BEFORE the iter-8 snapshot
        can capture the stalled state; the supervisor rewinds to the
        verified iter-4 snapshot; the fault's done-marker keeps the
        replay clean, so the recovered run is iteration-exact vs the
        uninterrupted baseline."""
        root = ws["root"]
        prefix = os.path.join(root, "nan_rewind", "s")
        fdir = os.path.join(root, "nan_rewind_faults")
        os.makedirs(fdir, exist_ok=True)
        r = _run_cli(ws["solver"], prefix, "-train_guard",
                     "-guard_max_skips", "2", "-max_restarts", "2",
                     faults="nan_grad:2:0:5", faults_dir=fdir)
        assert r.returncode == 0, \
            f"rc={r.returncode}\n{r.stdout[-1500:]}\n{r.stderr[-1500:]}"
        assert "exiting 88" in r.stderr
        assert "numeric divergence" in r.stderr
        assert "rewinding to the newest verified snapshot" in r.stderr
        assert "s_iter_4.solverstate" in r.stderr
        _assert_bitwise_equal(_final_weights(prefix), ws["baseline"])

    def test_anomaly_action_abort(self, ws):
        """anomaly_action abort: divergence is fatal — exit 88 with no
        restart (no faults_dir, so a restart would just re-diverge)."""
        root = ws["root"]
        prefix = os.path.join(root, "abort", "s")
        r = _run_cli(ws["solver"], prefix, "-train_guard",
                     "-guard_max_skips", "2", "-max_restarts", "2",
                     "-anomaly_action", "abort",
                     faults="nan_grad:2:0:5")
        assert r.returncode == resilience.EXIT_NUMERIC, r.stderr[-1500:]
        assert "anomaly_action 'abort'" in r.stderr
        assert "rewinding" not in r.stderr

    def test_corrupt_record_quarantine_replay_identical(self, ws):
        """Record 9 rots (durably — real bitrot survives restarts;
        index 9 because the net-build shape probe samples records 0-8
        and 15, and a corrupt PROBE record is a hard failure at open
        by design): both runs complete, journal identical substitution
        decisions, and produce identical final weights — quarantine is
        replay-deterministic."""
        root = ws["root"]
        runs = []
        for tag in ("q1", "q2"):
            prefix = os.path.join(root, tag, "s")
            r = _run_cli(ws["solver"], prefix, "-train_guard",
                         faults="record_corrupt:1:0:9")
            assert r.returncode == 0, r.stderr[-1500:]
            assert "quarantined record 9" in r.stderr
            q = json.load(open(prefix + ".quarantine.json"))
            runs.append((_final_weights(prefix), [
                (e["index"], e["substitute"], e["reason"])
                for e in q["records"]]))
        _assert_bitwise_equal(runs[0][0], runs[1][0])
        assert runs[0][1] == runs[1][1] == [
            (9, 10, runs[0][1][0][2])]
