"""Tests for pycaffe io (Transformer/oversample/conversions), Classifier,
stochastic pooling, and InfogainLoss-from-file."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import caffe_mpi_tpu.pycaffe as caffe
from caffe_mpi_tpu import caffe_io
from gradcheck import make_layer


class TestIO:
    def test_datum_conversions(self):
        arr = np.arange(12, dtype=np.uint8).reshape(3, 2, 2)
        buf = caffe_io.array_to_datum(arr, 5)
        back, label = caffe_io.datum_to_array(buf)
        np.testing.assert_array_equal(back, arr)
        assert label == 5

    def test_transformer_roundtrip(self, rng):
        t = caffe_io.Transformer({"data": (1, 3, 8, 8)})
        t.set_transpose("data", (2, 0, 1))
        t.set_channel_swap("data", (2, 1, 0))
        t.set_raw_scale("data", 255.0)
        t.set_mean("data", np.array([10.0, 20.0, 30.0]))
        img = rng.rand(8, 8, 3).astype(np.float32)
        pre = t.preprocess("data", img)
        assert pre.shape == (3, 8, 8)
        back = t.deprocess("data", pre)  # returns HWC (inverse of preprocess)
        np.testing.assert_allclose(back, img, atol=1e-4)

    def test_oversample(self, rng):
        imgs = [rng.rand(10, 10, 3).astype(np.float32)]
        crops = caffe_io.oversample(imgs, (8, 8))
        assert crops.shape == (10, 8, 8, 3)
        # mirrored second half
        np.testing.assert_allclose(crops[5], crops[0][:, ::-1, :])


class TestClassifier:
    def test_predict(self, tmp_path, rng):
        model = tmp_path / "deploy.prototxt"
        model.write_text("""
        name: "toy"
        layer { name: "data" type: "Input" top: "data"
                input_param { shape { dim: 4 dim: 3 dim: 8 dim: 8 } } }
        layer { name: "ip" type: "InnerProduct" bottom: "data" top: "score"
                inner_product_param { num_output: 5
                  weight_filler { type: "xavier" } } }
        layer { name: "prob" type: "Softmax" bottom: "score" top: "prob" }
        """)
        net = caffe.Net(str(model), caffe.TEST)
        w = str(tmp_path / "w.caffemodel")
        net.save(w)
        clf = caffe.Classifier(str(model), w, image_dims=(10, 10))
        imgs = [rng.rand(12, 12, 3).astype(np.float32) for _ in range(2)]
        preds = clf.predict(imgs, oversample=True)
        assert preds.shape == (2, 5)
        np.testing.assert_allclose(preds.sum(axis=1), 1.0, rtol=1e-4)
        preds2 = clf.predict(imgs, oversample=False)
        assert preds2.shape == (2, 5)


class TestStochasticPooling:
    def test_train_samples_within_window(self, rng):
        layer, params, state = make_layer(
            'name: "p" type: "Pooling" bottom: "x" top: "y"\n'
            'pooling_param { pool: STOCHASTIC kernel_size: 2 stride: 2 }',
            [(2, 3, 4, 4)],
        )
        x = jnp.abs(jnp.asarray(rng.randn(2, 3, 4, 4).astype(np.float32)))
        (y,), _ = layer.apply(params, state, [x], train=True,
                              rng=jax.random.PRNGKey(0))
        assert y.shape == (2, 3, 2, 2)
        # each output must be one of its window's elements
        xn, yn = np.array(x), np.array(y)
        for n in range(2):
            for c in range(3):
                for i in range(2):
                    for j in range(2):
                        win = xn[n, c, 2*i:2*i+2, 2*j:2*j+2].reshape(-1)
                        assert np.any(np.isclose(win, yn[n, c, i, j]))

    def test_ceil_mode_shape(self, rng):
        # 5x5, k=2, s=2: Caffe ceil mode -> 3x3 (declared == produced)
        layer, params, state = make_layer(
            'name: "p" type: "Pooling" bottom: "x" top: "y"\n'
            'pooling_param { pool: STOCHASTIC kernel_size: 2 stride: 2 }',
            [(1, 1, 5, 5)],
        )
        assert layer.out_shapes == [(1, 1, 3, 3)]
        x = jnp.abs(jnp.asarray(rng.randn(1, 1, 5, 5).astype(np.float32)))
        (y,), _ = layer.apply(params, state, [x], train=True,
                              rng=jax.random.PRNGKey(0))
        assert y.shape == (1, 1, 3, 3)
        (yt,), _ = layer.apply(params, state, [x], train=False, rng=None)
        assert yt.shape == (1, 1, 3, 3)

    def test_test_weighted_average(self, rng):
        layer, params, state = make_layer(
            'name: "p" type: "Pooling" bottom: "x" top: "y"\n'
            'pooling_param { pool: STOCHASTIC kernel_size: 2 stride: 2 }',
            [(1, 1, 2, 2)],
        )
        x = jnp.asarray([[[[1.0, 2.0], [3.0, 4.0]]]])
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        expect = (1 + 4 + 9 + 16) / (1 + 2 + 3 + 4)
        np.testing.assert_allclose(float(y[0, 0, 0, 0]), expect, rtol=1e-5)


class TestInfogainFromFile:
    def test_identity_H_matches_multinomial(self, tmp_path, rng):
        from caffe_mpi_tpu.io import save_blob_binaryproto
        H = np.eye(4, dtype=np.float32)
        hp = str(tmp_path / "H.binaryproto")
        save_blob_binaryproto(hp, H)
        layer, params, state = make_layer(
            f'name: "l" type: "InfogainLoss" bottom: "p" bottom: "t" top: "loss"\n'
            f'infogain_loss_param {{ source: "{hp}" }}',
            [(3, 4), (3,)],
        )
        prob = jax.nn.softmax(jnp.asarray(rng.randn(3, 4).astype(np.float32)))
        t = jnp.asarray(rng.randint(0, 4, 3))
        (loss,), _ = layer.apply(params, state, [prob, t], train=True, rng=None)
        picked = np.array(prob)[np.arange(3), np.array(t)]
        expect = -np.log(picked).sum() / 3
        np.testing.assert_allclose(float(loss), expect, rtol=1e-5)
