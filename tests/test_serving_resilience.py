"""Serving-plane resilience (ISSUE 12): load-shedding admission
control, request deadlines, the dispatch stall breaker (/healthz flip,
fast-fail, recovery), verified hot-swap with canary rollback, graceful
drain, and the typed HTTP error surface.

Reference behavior baseline: the reference deployment
(examples/web_demo/app.py) has none of this — an overloaded or hung
Classifier takes every client down with it. Here every failure mode is
typed, bounded, and journaled (docs/serving.md "Resilience").
"""

import json
import os
import threading
import time
import urllib.request
import urllib.error

import numpy as np
import pytest

import caffe_mpi_tpu.pycaffe as caffe
from caffe_mpi_tpu.serving import (DeadlineError, EngineClosedError,
                                   EngineUnhealthyError, ServingEngine,
                                   ShedError, SnapshotWatcher, SwapError)
from caffe_mpi_tpu.serving.http_front import make_server
from caffe_mpi_tpu.utils import resilience

TOY_NET = """
name: "toy"
layer {{ name: "data" type: "Input" top: "data"
        input_param {{ shape {{ dim: {batch} dim: 3 dim: 8 dim: 8 }} }} }}
layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "score"
        inner_product_param {{ num_output: 5
          weight_filler {{ type: "xavier" }} }} }}
layer {{ name: "prob" type: "Softmax" bottom: "score" top: "prob" }}
"""


def write_toy(tmp_path, batch=4, name="deploy.prototxt"):
    model = tmp_path / name
    model.write_text(TOY_NET.format(batch=batch))
    net = caffe.Net(str(model), caffe.TEST)
    weights = str(tmp_path / (name + ".caffemodel"))
    net.save(weights)
    return str(model), weights


def imgs(n, seed=0):
    r = np.random.RandomState(seed)
    return [r.rand(8, 8, 3).astype(np.float32) for _ in range(n)]


def wait_dispatcher_took(eng, timeout=5.0):
    """Block until the dispatcher has pulled every pending request into
    a batch. The stall-vs-deadline tests need request A *inside* its
    injected dispatch stall before request B is submitted; on a loaded
    host the dispatcher thread can lag both submits and A+B would ride
    one batch."""
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        with eng._batcher._cv:
            if not eng._batcher._pending:
                return
        time.sleep(0.005)
    raise TimeoutError("dispatcher never took the pending request")


def publish_snapshot(prefix, it, model_file, scale=3.0, weights_from=None):
    """Write a verified flat snapshot set (<prefix>_iter_<it>.caffemodel
    + .solverstate + crc32c manifest) whose ip weights are `scale`x the
    `weights_from` file's — the swap feed the watcher consumes."""
    net = caffe.Net(model_file, caffe.TEST)
    if weights_from:
        net.copy_from(weights_from)
    net.params["ip"][0].data = net.params["ip"][0].data * scale
    mpath = f"{prefix}_iter_{it}.caffemodel"
    net.save(mpath)
    spath = f"{prefix}_iter_{it}.solverstate"
    with open(spath, "wb") as f:  # the watcher never loads solver state
        f.write(b"state-stub")
    resilience.write_snapshot_manifest(spath, it,
                                       {"model": mpath, "state": spath})
    return mpath


@pytest.fixture
def faults():
    """Configure the fault plane for one test and always restore it."""
    def configure(spec):
        resilience.FAULTS.configure(spec)
    yield configure
    resilience.FAULTS.configure(os.environ.get("CAFFE_TPU_FAULTS", ""))


# ---------------------------------------------------------------------------
# load-shedding admission control (serve_queue_limit)

class TestAdmissionControl:
    def test_over_limit_submit_sheds_typed_and_depth_is_bounded(
            self, tmp_path):
        model, weights = write_toy(tmp_path)
        # a 60s window keeps the backlog parked in the queue
        with ServingEngine(window_ms=60_000, queue_limit=2) as eng:
            eng.load_model("m", model, weights)
            f1 = eng.submit("m", imgs(1)[0])
            f2 = eng.submit("m", imgs(1)[0])
            with pytest.raises(ShedError) as ei:
                eng.submit("m", imgs(1)[0])
            assert ei.value.http_status == 429 and ei.value.kind == "shed"
            st = eng.stats()
            assert st["shed_requests"] == 1
            assert st["max_queue_depth"] == 2  # held AT the limit
            assert not f1.done() and not f2.done()

    def test_deterministic_shed_count_under_overload(self, tmp_path):
        # offered load > capacity with the dispatcher parked: exactly
        # offered - limit submits shed, queue depth never passes limit.
        # The limit stays BELOW the max bucket (4), so a full group can
        # never close the 60s window early and drain mid-loop — the
        # exact counts are deterministic, not a race with the
        # dispatcher.
        model, weights = write_toy(tmp_path)
        with ServingEngine(window_ms=60_000, queue_limit=3) as eng:
            eng.load_model("m", model, weights)
            accepted = shed = 0
            for im in imgs(20):
                try:
                    eng.submit("m", im)
                    accepted += 1
                except ShedError:
                    shed += 1
            assert (accepted, shed) == (3, 17)
            assert eng.stats()["max_queue_depth"] == 3

    def test_zero_limit_is_unbounded(self, tmp_path):
        model, weights = write_toy(tmp_path)
        with ServingEngine(window_ms=60_000) as eng:  # default 0
            eng.load_model("m", model, weights)
            for im in imgs(12):
                eng.submit("m", im)
            assert eng.stats()["shed_requests"] == 0

    def test_negative_resilience_knobs_rejected_at_init(self):
        with pytest.raises(ValueError, match="serve_queue_limit"):
            ServingEngine(queue_limit=-1, start=False)
        with pytest.raises(ValueError, match="serve_deadline_ms"):
            ServingEngine(deadline_ms=-1, start=False)
        with pytest.raises(ValueError, match="serve_stall_s"):
            ServingEngine(stall_s=-0.5, start=False)


# ---------------------------------------------------------------------------
# request deadlines (serve_deadline_ms)

class TestDeadline:
    def test_request_aged_past_deadline_fails_typed(self, tmp_path,
                                                    faults):
        # the dispatcher is busy 0.6s inside request A's dispatch (an
        # injected stall, breaker OFF); request B, submitted right
        # behind it with a 100ms deadline, must fail typed at its
        # window close instead of riding a batch whose result it
        # would discard
        model, weights = write_toy(tmp_path)
        faults("serve_dispatch_stall:1:0:0.6")
        with ServingEngine(window_ms=0, deadline_ms=100) as eng:
            eng.load_model("m", model, weights)
            fa = eng.submit("m", imgs(1)[0])
            wait_dispatcher_took(eng)  # A is inside the stall, alone
            fb = eng.submit("m", imgs(1)[0])
            assert fa.result(timeout=10).shape == (5,)
            with pytest.raises(DeadlineError) as ei:
                fb.result(timeout=10)
            assert ei.value.http_status == 504
            assert ei.value.kind == "deadline"
            st = eng.stats()
            assert st["deadline_failures"] == 1

    def test_window_clamped_to_deadline(self, tmp_path):
        # a 60s window with a 150ms deadline must still dispatch the
        # request (the batch closes AT the deadline, not the window)
        model, weights = write_toy(tmp_path)
        with ServingEngine(window_ms=60_000, deadline_ms=150) as eng:
            eng.load_model("m", model, weights)
            t0 = time.perf_counter()
            out = eng.submit("m", imgs(1)[0]).result(timeout=10)
            assert out.shape == (5,)
            assert time.perf_counter() - t0 < 5.0
            assert eng.stats()["deadline_failures"] == 0

    def test_deadline_off_is_free(self, tmp_path):
        model, weights = write_toy(tmp_path)
        with ServingEngine(window_ms=0) as eng:
            eng.load_model("m", model, weights)
            assert eng.classify("m", imgs(3)).shape == (3, 5)
            assert eng.stats()["deadline_failures"] == 0


# ---------------------------------------------------------------------------
# dispatch stall breaker

class TestStallBreaker:
    def test_stall_trips_breaker_fast_fails_then_recovers(
            self, tmp_path, faults):
        model, weights = write_toy(tmp_path)
        faults("serve_dispatch_stall:1:0:1.5")
        with ServingEngine(window_ms=0, stall_s=0.3,
                           journal=str(tmp_path / "m")) as eng:
            eng.load_model("m", model, weights)
            fut = eng.submit("m", imgs(1)[0])
            # the in-flight future fails from the MONITOR thread while
            # the dispatch thread is still wedged in the 1.5s stall
            with pytest.raises(DeadlineError):
                fut.result(timeout=10)
            assert not eng.healthy
            h = eng.health()
            assert h["healthy"] is False
            assert h["breaker"]["state"] == "open"
            assert h["breaker"]["section"].startswith("dispatch:")
            # new requests fast-fail well inside the stall window
            t0 = time.perf_counter()
            with pytest.raises(EngineUnhealthyError) as ei:
                eng.submit("m", imgs(1)[0])
            assert time.perf_counter() - t0 < 0.3
            assert ei.value.http_status == 503
            assert ei.value.kind == "unhealthy"
            # journaled for the operator
            doc = json.load(open(str(tmp_path / "m") + ".serve.run.json"))
            assert doc["reason"].startswith("serve_stall:dispatch")
            # probe while the stalled call is still wedged: refused
            assert eng.probe_recovery(timeout=1) is False
            # the injected stall ends -> the wedge retires normally
            eng.drain(timeout=10)
            assert eng.probe_recovery(timeout=10) is True
            assert eng.healthy
            # serving resumes, zero new compiles through the whole trip
            assert eng.classify("m", imgs(2)).shape == (2, 5)
            st = eng.stats()
            assert st["stall_trips"] == 1
            assert st["healthy"] is True
            assert st["compile_count"] == st["warmed_buckets"]

    def test_trip_drains_parked_backlog_too(self, tmp_path, faults):
        # a request PARKED in the queue when the breaker trips has a
        # wedged dispatcher — it must fail typed with the in-flight
        # one, not stay PENDING forever
        model, weights = write_toy(tmp_path)
        faults("serve_dispatch_stall:1:0:1.2")
        with ServingEngine(window_ms=0, stall_s=0.3) as eng:
            eng.load_model("m", model, weights)
            fa = eng.submit("m", imgs(1)[0])  # wedges the dispatcher
            fb = eng.submit("m", imgs(1)[0])  # parks behind it
            with pytest.raises(DeadlineError):
                fa.result(timeout=10)
            with pytest.raises(DeadlineError):
                fb.result(timeout=10)
            eng.drain(timeout=10)

    def test_close_stops_breaker_monitor_thread(self, tmp_path):
        # an embedding app cycling engines must not leak one watchdog
        # poller per engine
        model, weights = write_toy(tmp_path)
        eng = ServingEngine(window_ms=0, stall_s=5.0)
        eng.load_model("m", model, weights)
        wd = eng._watchdog
        assert wd is not None and wd._thread.is_alive()
        eng.close()
        wd._thread.join(timeout=5)
        assert not wd._thread.is_alive()
        assert eng._watchdog is None

    def test_probe_recovery_after_close_does_not_rearm(self, tmp_path):
        # a recovery-probe thread that loses the race with close() must
        # not re-arm a fresh watchdog (a monitor thread nobody would
        # ever stop) or flip a closed engine back to healthy
        model, weights = write_toy(tmp_path)
        eng = ServingEngine(window_ms=0, stall_s=5.0)
        eng.load_model("m", model, weights)
        eng._on_stall("dispatch:m", 9.9)   # breaker open
        assert not eng.healthy
        eng.close()
        assert eng.probe_recovery(timeout=5) is False
        assert eng._watchdog is None
        assert not eng.healthy

    def test_breaker_off_by_default(self, tmp_path):
        model, weights = write_toy(tmp_path)
        with ServingEngine(window_ms=0) as eng:
            eng.load_model("m", model, weights)
            assert eng._watchdog is None  # zero threads when off
            assert eng.health()["healthy"] is True


# ---------------------------------------------------------------------------
# verified hot-swap + canary rollback

class TestHotSwap:
    def _engine(self, tmp_path, **kw):
        model, weights = write_toy(tmp_path)
        eng = ServingEngine(window_ms=0, journal=str(tmp_path / "m"), **kw)
        eng.load_model("m", model, weights)
        return eng, model, weights

    def test_watch_swaps_newly_verified_snapshot_zero_recompiles(
            self, tmp_path):
        eng, model, weights = self._engine(tmp_path)
        with eng:
            prefix = str(tmp_path / "train" / "snap")
            os.makedirs(os.path.dirname(prefix))
            watcher = SnapshotWatcher(eng, "m", prefix, poll_s=0.1)
            base = eng.classify("m", imgs(3, seed=7))
            assert watcher.check_once() is False  # nothing published yet
            w2 = publish_snapshot(prefix, 10, model, scale=3.0,
                                  weights_from=weights)
            compiles = eng.compile_count
            assert watcher.check_once() is True
            assert eng.swaps == 1
            # the swap compiled NOTHING: same bucket programs, new bytes
            assert eng.compile_count == compiles
            assert eng.compile_count == eng.warmed_buckets
            got = eng.classify("m", imgs(3, seed=7))
            assert not np.allclose(got, base)
            # scores now match a cold classifier on the new weights
            clf = caffe.Classifier(model, w2, image_dims=(8, 8))
            want = clf.predict(imgs(3, seed=7), oversample=False)
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
            doc = json.load(open(str(tmp_path / "m") + ".serve.run.json"))
            assert doc["reason"] == "swap"
            assert doc["source"] == "iter_10"

    def test_corrupt_swap_rejected_previous_weights_bitwise(
            self, tmp_path, faults):
        eng, model, weights = self._engine(tmp_path)
        with eng:
            prefix = str(tmp_path / "snap")
            base = eng.classify("m", imgs(2, seed=1))
            publish_snapshot(prefix, 5, model, weights_from=weights)
            # post-manifest bitrot: verify must reject before any byte
            # reaches the engine
            faults("swap_corrupt:1")
            watcher = SnapshotWatcher(eng, "m", prefix, poll_s=0.1)
            assert watcher.check_once() is False
            assert eng.swaps == 0 and eng.swap_rejections == 1
            after = eng.classify("m", imgs(2, seed=1))
            np.testing.assert_array_equal(base, after)  # BITWISE same
            doc = json.load(open(str(tmp_path / "m") + ".serve.run.json"))
            assert doc["reason"] == "swap_rejected"
            assert "crc" in doc["swap_reason"]
            # rot does not heal: the iteration is blacklisted, a later
            # GOOD snapshot still swaps
            publish_snapshot(prefix, 6, model, scale=2.0,
                             weights_from=weights)
            assert watcher.check_once() is True
            assert eng.swaps == 1

    def test_canary_rollback_on_nonfinite_scores(self, tmp_path, faults):
        eng, model, weights = self._engine(tmp_path)
        with eng:
            prefix = str(tmp_path / "snap")
            base = eng.classify("m", imgs(2, seed=2))
            publish_snapshot(prefix, 7, model, weights_from=weights)
            faults("swap_canary_bad:1")
            watcher = SnapshotWatcher(eng, "m", prefix, poll_s=0.1)
            assert watcher.check_once() is False
            assert eng.swap_rejections == 1 and eng.swaps == 0
            after = eng.classify("m", imgs(2, seed=2))
            np.testing.assert_array_equal(base, after)
            doc = json.load(open(str(tmp_path / "m") + ".serve.run.json"))
            assert doc["reason"] == "swap_rejected"
            assert "non-finite" in doc["swap_reason"]

    def test_shape_mismatched_weights_rejected_by_canary(self, tmp_path):
        # a snapshot from a DIFFERENT architecture (10-way head) loads
        # as a file but cannot fit the compiled programs' params tree
        eng, model, weights = self._engine(tmp_path)
        with eng:
            other = tmp_path / "other.prototxt"
            other.write_text(TOY_NET.format(batch=4).replace(
                "num_output: 5", "num_output: 10"))
            onet = caffe.Net(str(other), caffe.TEST)
            ow = str(tmp_path / "other.caffemodel")
            onet.save(ow)
            base = eng.classify("m", imgs(2, seed=3))
            with pytest.raises(SwapError):
                eng.swap_weights("m", ow)
            assert eng.swap_rejections == 1
            after = eng.classify("m", imgs(2, seed=3))
            np.testing.assert_array_equal(base, after)

    def test_swap_under_live_traffic_all_futures_resolve(self, tmp_path):
        eng, model, weights = self._engine(tmp_path)
        with eng:
            prefix = str(tmp_path / "snap")
            w2 = publish_snapshot(prefix, 3, model, scale=3.0,
                                  weights_from=weights)
            futures = []
            stop = threading.Event()

            def traffic():
                while not stop.is_set():
                    futures.append(eng.submit("m", imgs(1)[0]))
                    time.sleep(0.002)

            t = threading.Thread(target=traffic, daemon=True)
            t.start()
            time.sleep(0.05)
            eng.swap_weights("m", w2)
            time.sleep(0.05)
            stop.set()
            t.join(timeout=5)
            eng.drain(timeout=30)
            rows = [f.result(timeout=5) for f in futures]
            assert all(r.shape == (5,) for r in rows)
            assert eng.compile_count == eng.warmed_buckets
            assert eng.swaps == 1

    def test_orbax_sets_are_skipped_not_rejected(self, tmp_path):
        eng, model, weights = self._engine(tmp_path)
        with eng:
            prefix = str(tmp_path / "snap")
            d = f"{prefix}_iter_4.orbax"
            os.makedirs(d)
            with open(os.path.join(d, "shard0"), "wb") as f:
                f.write(b"shard-bytes")
            resilience.write_sharded_manifest(d, 4)
            watcher = SnapshotWatcher(eng, "m", prefix, poll_s=0.1)
            assert watcher.check_once() is False
            assert eng.swap_rejections == 0  # skip, not a rejection


# ---------------------------------------------------------------------------
# graceful drain

class TestGracefulDrain:
    def test_shutdown_resolves_every_inflight_future(self, tmp_path):
        model, weights = write_toy(tmp_path)
        eng = ServingEngine(window_ms=60_000)  # window parks the batch
        eng.load_model("m", model, weights)
        futs = [eng.submit("m", im) for im in imgs(3)]
        eng.shutdown(timeout=30)  # stop accepting -> flush -> resolve
        rows = [f.result(timeout=1) for f in futs]  # NOT cancelled
        assert all(r.shape == (5,) for r in rows)
        with pytest.raises(EngineClosedError) as ei:
            eng.submit("m", imgs(1)[0])
        assert ei.value.http_status == 503 and ei.value.kind == "closed"

    def test_shutdown_idempotent_and_empty(self, tmp_path):
        model, weights = write_toy(tmp_path)
        eng = ServingEngine(window_ms=0)
        eng.load_model("m", model, weights)
        eng.shutdown()
        eng.shutdown()  # second call is a no-op, not a hang


# ---------------------------------------------------------------------------
# typed HTTP surface (/healthz, /readyz, 429/503/504, 400 stays 400)

class _Server:
    def __init__(self, eng):
        self.srv = make_server(eng, "m", port=0)
        self.port = self.srv.server_address[1]
        self.thread = threading.Thread(target=self.srv.serve_forever,
                                       daemon=True)
        self.thread.start()

    def get(self, path):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}{path}", timeout=30) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def post_png(self, data=None):
        import io as _io
        from PIL import Image
        if data is None:
            buf = _io.BytesIO()
            Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(
                buf, format="PNG")
            data = buf.getvalue()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}/classify", data=data,
            headers={"Content-Type": "image/png"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def close(self):
        self.srv.shutdown()


class TestHttpFront:
    def test_healthz_readyz_and_stats_roundtrip(self, tmp_path):
        model, weights = write_toy(tmp_path)
        with ServingEngine(window_ms=0) as eng:
            eng.load_model("m", model, weights)
            web = _Server(eng)
            try:
                code, doc = web.get("/healthz")
                assert code == 200 and doc["healthy"] is True
                assert "last_dispatch_age_s" in doc
                code, doc = web.get("/readyz")
                assert code == 200 and doc["ready"] is True
                assert doc["compile_count"] == doc["warmed_buckets"]
                code, doc = web.get("/stats")
                assert code == 200 and doc["healthy"] is True
            finally:
                web.close()

    def test_readyz_503_with_empty_zoo(self):
        with ServingEngine(window_ms=0) as eng:
            web = _Server(eng)
            try:
                code, doc = web.get("/readyz")
                assert code == 503 and doc["ready"] is False
            finally:
                web.close()

    def test_shed_is_429_with_machine_readable_body(self, tmp_path):
        model, weights = write_toy(tmp_path)
        with ServingEngine(window_ms=60_000, queue_limit=1) as eng:
            eng.load_model("m", model, weights)
            eng.submit("m", imgs(1)[0])  # fills the backlog
            web = _Server(eng)
            try:
                code, doc = web.post_png()
                assert code == 429
                assert doc["kind"] == "shed"
                assert "serve_queue_limit" in doc["error"]
            finally:
                web.close()

    def test_breaker_open_is_503_and_healthz_flips(self, tmp_path,
                                                   faults):
        model, weights = write_toy(tmp_path)
        faults("serve_dispatch_stall:1:0:1.0")
        with ServingEngine(window_ms=0, stall_s=0.25) as eng:
            eng.load_model("m", model, weights)
            web = _Server(eng)
            try:
                fut = eng.submit("m", imgs(1)[0])  # trips the breaker
                with pytest.raises(DeadlineError):
                    fut.result(timeout=10)
                code, doc = web.get("/healthz")
                assert code == 503 and doc["healthy"] is False
                code, doc = web.post_png()
                assert code == 503 and doc["kind"] == "unhealthy"
                eng.drain(timeout=10)
            finally:
                web.close()

    def test_deadline_is_504_over_http(self, tmp_path, faults):
        model, weights = write_toy(tmp_path)
        faults("serve_dispatch_stall:1:0:0.6")
        with ServingEngine(window_ms=0, deadline_ms=100) as eng:
            eng.load_model("m", model, weights)
            web = _Server(eng)
            try:
                fa = eng.submit("m", imgs(1)[0])  # occupies dispatcher
                wait_dispatcher_took(eng)
                code, doc = web.post_png()
                assert code == 504 and doc["kind"] == "deadline"
                fa.result(timeout=10)
            finally:
                web.close()

    def test_closed_engine_is_503(self, tmp_path):
        model, weights = write_toy(tmp_path)
        eng = ServingEngine(window_ms=0)
        eng.load_model("m", model, weights)
        web = _Server(eng)
        try:
            eng.close()
            code, doc = web.post_png()
            assert code == 503 and doc["kind"] == "closed"
        finally:
            web.close()

    def test_bad_upload_stays_400(self, tmp_path):
        model, weights = write_toy(tmp_path)
        with ServingEngine(window_ms=0) as eng:
            eng.load_model("m", model, weights)
            web = _Server(eng)
            try:
                code, doc = web.post_png(data=b"this is not an image")
                assert code == 400
                assert doc["kind"] == "bad_request"
                assert "decode" in doc["error"]
            finally:
                web.close()


# ---------------------------------------------------------------------------
# failure-path behavior fixes (ISSUE 20): the sites the new lint passes
# flagged and we FIXED rather than waived — each fix gets a regression


class TestFailurePathLiveness:
    def test_dispatcher_crash_is_contained_typed_and_journaled(
            self, tmp_path):
        """thread-crash fix: an exception out of the dispatch loop must
        fail in-flight futures TYPED, journal serve_dispatcher_crash,
        and re-enter the loop — never die silently with the backlog
        parked behind a dead thread (the PR 11 wedge, as a crash)."""
        model, weights = write_toy(tmp_path)
        journal = str(tmp_path / "serve")
        with ServingEngine(window_ms=0, journal=journal) as eng:
            eng.load_model("m", model, weights)
            # prove the path works before the injected crash
            assert eng.classify("m", imgs(1)).shape == (1, 5)
            real = eng._batcher._take_group
            state = {"armed": True}

            def boom(*a, **kw):
                if state["armed"]:
                    state["armed"] = False
                    raise RuntimeError("injected dispatcher crash")
                return real(*a, **kw)

            eng._batcher._take_group = boom
            fut = eng.submit("m", imgs(1)[0])
            with pytest.raises(EngineUnhealthyError) as ei:
                fut.result(timeout=10)
            assert "dispatcher crashed" in str(ei.value)
            # fail_inflight resolves the future BEFORE the journal
            # write lands — poll briefly for the manifest
            jpath = journal + ".serve.run.json"
            deadline = time.perf_counter() + 5.0
            while not os.path.exists(jpath) \
                    and time.perf_counter() < deadline:
                time.sleep(0.01)
            doc = json.load(open(jpath))
            assert doc["reason"] == "serve_dispatcher_crash"
            assert "injected dispatcher crash" in doc["error"]
            # the loop re-entered: the SAME thread serves the next one
            assert eng.classify("m", imgs(1, seed=1)).shape == (1, 5)

    def test_shed_submit_constructs_no_future(self, tmp_path, monkeypatch):
        """future-resolution fix (the PR 7 shape): an admission raise —
        shed, closed, unhealthy — must happen BEFORE the request and
        its Future exist, so a rejected submit can never strand a
        pending-forever future."""
        from caffe_mpi_tpu.serving import batcher as batcher_mod
        model, weights = write_toy(tmp_path)
        built = []
        real_req = batcher_mod._Request

        def counting_req(*a, **kw):
            r = real_req(*a, **kw)
            built.append(r)
            return r

        monkeypatch.setattr(batcher_mod, "_Request", counting_req)
        with ServingEngine(window_ms=60_000, queue_limit=1) as eng:
            eng.load_model("m", model, weights)
            eng.submit("m", imgs(1)[0])
            assert len(built) == 1
            with pytest.raises(ShedError):
                eng.submit("m", imgs(1)[0])
            assert len(built) == 1  # the shed built nothing
            eng._healthy = False
            with pytest.raises(EngineUnhealthyError):
                eng.submit("m", imgs(1)[0])
            eng._healthy = True
            assert len(built) == 1
        with pytest.raises(EngineClosedError):
            eng.submit("m", imgs(1)[0])
        assert len(built) == 1

    def test_probe_thread_crash_journals_not_silent(self, tmp_path):
        """thread-crash fix: the async recovery-probe thread entry must
        catch a raising probe_recovery and journal serve_probe_crash —
        a silent death leaves the breaker open with no signal."""
        model, weights = write_toy(tmp_path)
        journal = str(tmp_path / "probe")
        with ServingEngine(window_ms=0, journal=journal) as eng:
            eng.load_model("m", model, weights)
            eng.probe_recovery = lambda *a, **kw: (_ for _ in ()).throw(
                RuntimeError("injected probe crash"))
            eng._probe_recovery_guarded()  # must not raise
            doc = json.load(open(journal + ".serve.run.json"))
            assert doc["reason"] == "serve_probe_crash"
            assert "injected probe crash" in doc["error"]

    def test_classify_gather_is_deadline_bounded(self, tmp_path):
        """deadline-discipline fix: classify's future gather takes a
        timeout — a wedged dispatcher surfaces as TimeoutError in the
        caller, never an unbounded f.result() hang."""
        import concurrent.futures as cf
        model, weights = write_toy(tmp_path)
        with ServingEngine(window_ms=0) as eng:
            eng.load_model("m", model, weights)
            eng.submit = lambda *a, **kw: cf.Future()  # never resolves
            t0 = time.perf_counter()
            with pytest.raises(cf.TimeoutError):
                eng.classify("m", imgs(1), timeout=0.2)
            assert time.perf_counter() - t0 < 5.0

    def test_wait_snapshots_join_is_bounded(self):
        """deadline-discipline fix: a wedged async snapshot writer
        (dead-tunnel device fetch) must fail wait_snapshots loudly
        within the timeout, not hang the exit path forever."""
        from caffe_mpi_tpu.solver.solver import Solver

        class Stub:
            pass

        stub = Stub()
        release = threading.Event()
        stub._snapshot_thread = threading.Thread(
            target=release.wait, args=(10.0,), daemon=True)
        stub._snapshot_thread.start()
        try:
            with pytest.raises(RuntimeError) as ei:
                Solver.wait_snapshots(stub, timeout=0.1)
            assert "wedged" in str(ei.value)
        finally:
            release.set()
            stub._snapshot_thread.join(5.0)

    def test_wait_snapshots_reraises_writer_error_after_join(self):
        """The bounded join must still deliver a finished writer's
        failure: a checkpoint the user believes exists but doesn't
        must not pass silently."""
        from caffe_mpi_tpu.solver.solver import Solver

        class Stub:
            pass

        stub = Stub()
        stub._snapshot_thread = None
        stub._snapshot_error = (700, OSError("disk full"))
        with pytest.raises(RuntimeError) as ei:
            Solver.wait_snapshots(stub, timeout=0.1)
        assert "iteration 700" in str(ei.value)
        assert stub._snapshot_error is None
