"""Host-sync lint (tools/check_host_syncs.py) — the per-iteration-RTT
bug class CLAUDE.md warns about, caught mechanically instead of by
advisor review: float()/np.asarray()/.item()/device_get inside a
for/while loop in the solver/parallel hot paths fails tier-1 unless the
statement carries an explicit `# host-sync: ok` waiver.
"""

import importlib.util
import os
import textwrap

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    spec = importlib.util.spec_from_file_location(
        "check_host_syncs", os.path.join(_ROOT, "tools",
                                         "check_host_syncs.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_hot_paths_are_clean():
    """The shipped solver/parallel modules pass the lint: every host
    materialization in a loop is either gone or explicitly waived."""
    lint = _load()
    findings = lint.scan_paths([
        os.path.join(_ROOT, "caffe_mpi_tpu", "solver"),
        os.path.join(_ROOT, "caffe_mpi_tpu", "parallel"),
    ])
    assert findings == [], (
        "host-sync calls inside hot loops (fix or waive with "
        f"'# host-sync: ok'): {findings}")


def test_lint_flags_loop_syncs(tmp_path):
    src = textwrap.dedent("""
        import numpy as np

        def train(losses):
            total = 0.0
            for l in losses:
                total += float(l)          # per-iteration RTT: flagged
            while losses:
                x = np.asarray(losses.pop())
                y = losses[0].item()
            return total, float(total)     # outside any loop: clean
    """)
    p = tmp_path / "hot.py"
    p.write_text(src)
    lint = _load()
    kinds = sorted(k for (_, _, k) in lint.scan_file(str(p)))
    assert kinds == [".item()", "float", "np.asarray"]


def test_lint_flags_comprehension_syncs(tmp_path):
    """Comprehensions are loops: the per-element sync pattern must not
    escape by being written as a listcomp/genexpr."""
    src = textwrap.dedent("""
        import numpy as np

        def gather(losses):
            a = [float(l) for l in losses]           # flagged
            b = sum(l.item() for l in losses)        # flagged
            c = {k: np.asarray(v) for k, v in losses}  # flagged
            return a, b, c, float(len(a))            # once: clean
    """)
    p = tmp_path / "comp.py"
    p.write_text(src)
    lint = _load()
    kinds = sorted(k for (_, _, k) in lint.scan_file(str(p)))
    assert kinds == [".item()", "float", "np.asarray"]


def test_lint_honors_waivers(tmp_path):
    src = textwrap.dedent("""
        import numpy as np

        def display(window):
            for l in window:
                s = float(l)  # host-sync: ok (display boundary)
                # host-sync: ok — already a host ndarray
                v = np.asarray(l)
    """)
    p = tmp_path / "waived.py"
    p.write_text(src)
    lint = _load()
    assert lint.scan_file(str(p)) == []


def test_lint_spans_multiline_statements(tmp_path):
    src = textwrap.dedent("""
        def log_line(log, window, rate):
            while window:
                log.info("loss = %.6g lr = %.6g",  # host-sync: ok
                         float(window.pop()),
                         float(rate))
    """)
    p = tmp_path / "multiline.py"
    p.write_text(src)
    lint = _load()
    assert lint.scan_file(str(p)) == []


def test_lint_surfaces_syntax_errors(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def oops(:\n")
    lint = _load()
    findings = lint.scan_file(str(p))
    assert len(findings) == 1 and "SYNTAX ERROR" in findings[0][2]
