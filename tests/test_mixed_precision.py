"""Mixed-precision tests: FLOAT16 (-> bfloat16 on TPU) compute policy with
f32 master weights and global_grad_scale loss scaling — the reference's
fp16 system (caffe.proto:124-130, net.cpp:815-818, Tensor conversion)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.net import Net
from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from caffe_mpi_tpu.solver import Solver

BF16_NET = """
name: "bf16net"
default_forward_type: FLOAT16
default_backward_type: FLOAT16
layer { name: "in" type: "Input" top: "data" top: "label"
        input_param { shape { dim: 16 dim: 1 dim: 12 dim: 12 }
                      shape { dim: 16 } } }
layer { name: "conv" type: "Convolution" bottom: "data" top: "c"
        convolution_param { num_output: 8 kernel_size: 3
          weight_filler { type: "msra" } } }
layer { name: "bn" type: "BatchNorm" bottom: "c" top: "c"
        batch_norm_param { scale_bias: true } }
layer { name: "r" type: "ReLU" bottom: "c" top: "c" }
layer { name: "ip" type: "InnerProduct" bottom: "c" top: "logits"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits" bottom: "label"
        top: "loss" }
"""


class TestBF16:
    def test_dtype_flow(self, rng):
        net = Net(NetParameter.from_text(BF16_NET), phase="TRAIN")
        params, state = net.init(jax.random.PRNGKey(0))
        # master weights stay f32 (solver_data_type FLOAT)
        assert params["conv"]["weight"].dtype == jnp.float32
        feeds = {"data": jnp.asarray(rng.randn(16, 1, 12, 12).astype(np.float32)),
                 "label": jnp.asarray(rng.randint(0, 4, 16))}
        blobs, _, loss = net.apply(params, state, feeds, train=True,
                                   rng=jax.random.PRNGKey(1))
        assert blobs["c"].dtype == jnp.bfloat16          # activations bf16
        assert blobs["logits"].dtype == jnp.bfloat16
        assert loss.dtype == jnp.float32                  # loss accumulated f32

    def test_per_layer_override(self, rng):
        text = BF16_NET.replace(
            'layer { name: "ip" type: "InnerProduct"',
            'layer { name: "ip" type: "InnerProduct" forward_type: FLOAT')
        net = Net(NetParameter.from_text(text), phase="TRAIN")
        params, state = net.init(jax.random.PRNGKey(0))
        feeds = {"data": jnp.asarray(rng.randn(16, 1, 12, 12).astype(np.float32)),
                 "label": jnp.asarray(rng.randint(0, 4, 16))}
        blobs, _, _ = net.apply(params, state, feeds, train=False)
        assert blobs["c"].dtype == jnp.bfloat16
        assert blobs["logits"].dtype == jnp.float32  # layer-level override

    def test_bf16_training_with_loss_scaling(self, rng):
        sp = SolverParameter.from_text(
            'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" max_iter: 40 '
            'type: "SGD" global_grad_scale: 128')
        sp.net_param = NetParameter.from_text(BF16_NET)
        s = Solver(sp)
        templates = rng.randn(4, 1, 12, 12).astype(np.float32)

        def feed(it):
            r = np.random.RandomState(it)
            lab = r.randint(0, 4, 16)
            return {"data": jnp.asarray(
                        templates[lab] + 0.2 * r.randn(16, 1, 12, 12).astype(np.float32)),
                    "label": jnp.asarray(lab)}

        l0 = s.step(1, feed)
        lN = s.step(39, feed)
        assert lN < 0.3 * l0
        # loss scaling must not leak into reported loss or update magnitude
        assert lN < 10
