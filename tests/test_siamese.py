"""Siamese-network integration test (reference examples/siamese):
cross-layer weight sharing by param name + ContrastiveLoss training —
similar pairs pulled together, dissimilar pushed apart."""

import numpy as np
import jax
import jax.numpy as jnp

from caffe_mpi_tpu.net import Net
from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from caffe_mpi_tpu.solver import Solver
from caffe_mpi_tpu.coord_map import coord_map_from_to


class TestSiamese:
    def test_towers_share_weights(self):
        net = Net(NetParameter.from_file("examples/siamese/mnist_siamese.prototxt"))
        params, _ = net.init(jax.random.PRNGKey(0))
        # second tower owns nothing: every param aliases tower one
        assert "conv1_p" not in params and "feat_p" not in params
        assert net.param_aliases[("conv1_p", "weight")] == ("conv1", "weight")

    def test_contrastive_training_separates(self, rng):
        sp = SolverParameter.from_text(
            'base_lr: 0.01 momentum: 0.9 lr_policy: "fixed" max_iter: 80 '
            'type: "SGD"')
        sp.net_param = NetParameter.from_file(
            "examples/siamese/mnist_siamese.prototxt")
        solver = Solver(sp)
        templates = rng.randn(4, 1, 28, 28).astype(np.float32)

        def feed(it):
            r = np.random.RandomState(it)
            a_cls = r.randint(0, 4, 32)
            sim = r.randint(0, 2, 32)
            b_cls = np.where(sim, a_cls, (a_cls + 1 + r.randint(0, 3, 32)) % 4)
            mk = lambda cls: templates[cls] + 0.15 * r.randn(32, 1, 28, 28).astype(np.float32)
            return {"data": jnp.asarray(mk(a_cls)),
                    "data_p": jnp.asarray(mk(b_cls)),
                    "sim": jnp.asarray(sim.astype(np.float32))}

        l0 = solver.step(1, feed)
        lN = solver.step(79, feed)
        assert lN < 0.5 * l0, f"contrastive loss not decreasing: {l0} -> {lN}"
        # embeddings: same-class pairs closer than cross-class
        fd = feed(10_000)
        blobs, _, _ = solver.net.apply(solver.params, solver.net_state, fd,
                                       train=False)
        d = np.linalg.norm(np.array(blobs["feat"]) - np.array(blobs["feat_p"]),
                           axis=1)
        sim = np.array(fd["sim"])
        assert d[sim == 1].mean() < d[sim == 0].mean()


class TestCoordMap:
    def test_conv_pool_composition(self):
        net = NetParameter.from_text("""
        layer { name: "in" type: "Input" top: "data"
                input_param { shape { dim: 1 dim: 1 dim: 64 dim: 64 } } }
        layer { name: "c" type: "Convolution" bottom: "data" top: "c"
                convolution_param { num_output: 1 kernel_size: 3 pad: 1 } }
        layer { name: "p" type: "Pooling" bottom: "c" top: "p"
                pooling_param { kernel_size: 2 stride: 2 } }
        """)
        scale, offset = coord_map_from_to(net, "data", "p")
        # pool stride 2: a data pixel maps to half-res coords
        assert scale == 0.5
        scale2, _ = coord_map_from_to(net, "p", "data")
        assert scale2 == 2.0
