"""Workflow examples stay green: finetune (+-weights transfer, faster
convergence) and the extract_features verification it performs.

Mirrors the reference's examples/finetune_flickr_style workflow +
tools/extract_features.cpp (SURVEY §2.8); the example itself asserts
(a) the finetuned run beats from-scratch and (b) the HDF5 feature dump
matches a direct forward — this test just drives it at reduced scale.
"""

import importlib.util
import os

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.mark.slow
def test_mnist_example_self_asserts(monkeypatch):
    """The flagship example's success criterion is enforced in-process:
    run_example parses the final TestAll accuracy and fails below the
    published threshold (reference examples/mnist/readme.md convention).
    300 iters is past the documented convergence length (250), so the
    0.99 bar is ACTIVE in this run, not skipped."""
    monkeypatch.chdir(_ROOT)
    spec = importlib.util.spec_from_file_location(
        "mnist_run", os.path.join(_ROOT, "examples/mnist/run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["-max_iter", "300"]) == 0


@pytest.mark.slow
def test_finetune_example_end_to_end(monkeypatch):
    monkeypatch.chdir(_ROOT)
    spec = importlib.util.spec_from_file_location(
        "finetune_run", os.path.join(_ROOT, "examples/finetune/run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["-pretrain_iter", "80", "-finetune_iter", "30"]) == 0


@pytest.mark.slow
def test_hdf5_classification_example(monkeypatch):
    monkeypatch.chdir(_ROOT)
    spec = importlib.util.spec_from_file_location(
        "hdf5_run", os.path.join(_ROOT, "examples/hdf5_classification/run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["-max_iter", "600"]) == 0


def test_net_surgery_example(monkeypatch):
    monkeypatch.chdir(_ROOT)
    spec = importlib.util.spec_from_file_location(
        "surgery_run", os.path.join(_ROOT, "examples/net_surgery/run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([]) == 0


def test_feature_extraction_example(monkeypatch):
    """ImageData file-list -> extract_features -> dump verified against
    a direct forward (reference examples/feature_extraction)."""
    monkeypatch.chdir(_ROOT)
    spec = importlib.util.spec_from_file_location(
        "featext_run",
        os.path.join(_ROOT, "examples/feature_extraction/run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["-batches", "2"]) == 0


def test_pycaffe_example(monkeypatch):
    """NetSpec caffenet parity + gradient-exact Python loss layer
    (reference examples/pycaffe)."""
    monkeypatch.chdir(_ROOT)
    spec = importlib.util.spec_from_file_location(
        "pycaffe_run", os.path.join(_ROOT, "examples/pycaffe/run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([]) == 0


@pytest.mark.slow
def test_solvers_example(monkeypatch):
    """All six optimizer recipes converge (reference examples/solvers)."""
    monkeypatch.chdir(_ROOT)
    spec = importlib.util.spec_from_file_location(
        "solvers_run", os.path.join(_ROOT, "examples/solvers/run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([]) == 0


@pytest.mark.slow
def test_cpp_classification_example(monkeypatch):
    """The embedded-CPython C++ classifier builds and prints the
    reference's top-5 output format (examples/cpp_classification)."""
    import shutil
    if not (shutil.which("g++") and shutil.which("python3-config")):
        pytest.skip("no C++ toolchain")
    monkeypatch.chdir(_ROOT)
    spec = importlib.util.spec_from_file_location(
        "cppc_run",
        os.path.join(_ROOT, "examples/cpp_classification/run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([]) == 0
