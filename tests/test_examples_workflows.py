"""Workflow examples stay green: finetune (+-weights transfer, faster
convergence) and the extract_features verification it performs.

Mirrors the reference's examples/finetune_flickr_style workflow +
tools/extract_features.cpp (SURVEY §2.8); the example itself asserts
(a) the finetuned run beats from-scratch and (b) the HDF5 feature dump
matches a direct forward — this test just drives it at reduced scale.
"""

import importlib.util
import os

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.mark.slow
def test_finetune_example_end_to_end(monkeypatch):
    monkeypatch.chdir(_ROOT)
    spec = importlib.util.spec_from_file_location(
        "finetune_run", os.path.join(_ROOT, "examples/finetune/run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["-pretrain_iter", "80", "-finetune_iter", "30"]) == 0


@pytest.mark.slow
def test_hdf5_classification_example(monkeypatch):
    monkeypatch.chdir(_ROOT)
    spec = importlib.util.spec_from_file_location(
        "hdf5_run", os.path.join(_ROOT, "examples/hdf5_classification/run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["-max_iter", "600"]) == 0


def test_net_surgery_example(monkeypatch):
    monkeypatch.chdir(_ROOT)
    spec = importlib.util.spec_from_file_location(
        "surgery_run", os.path.join(_ROOT, "examples/net_surgery/run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([]) == 0
