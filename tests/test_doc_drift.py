"""Doc-drift tripwires (ISSUE 4 satellite): the fault-injection site
list is load-bearing operator documentation — a site added at a call
site but missing from docs/robustness.md (or documented but deleted
from the code) silently rots the runbook. Three sources of truth are
held equal:

  1. the registry: `utils/resilience.FAULT_SITES`
  2. the docs:     the `Sites:` list in docs/robustness.md
  3. the code:     literal site names at FAULTS call sites

Pure text/AST checks — no jax, no device work; tier-1 cheap.
"""

import os
import re

from caffe_mpi_tpu.utils.resilience import FAULT_SITES

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every FaultPlane entry point a production call site can name a site
# through (fire/fire_at and the one-line helpers)
_HELPERS = ("fire", "fire_at", "active", "maybe_raise", "maybe_stall",
            "maybe_exit", "corrupt_file", "corrupt_bytes")
_CALL_RE = re.compile(
    r"\.(?:%s)\(\s*[\"']([a-z_]+)[\"']" % "|".join(_HELPERS))

# source trees whose FAULTS call sites are production injection points
# (tests configure sites by string; they are consumers, not sites)
_SCAN = ("caffe_mpi_tpu", "tools", "bench.py")


def _doc_sites() -> set[str]:
    with open(os.path.join(_ROOT, "docs", "robustness.md")) as f:
        text = f.read()
    m = re.search(r"Sites:\s*(.*?)\.\s", text, re.DOTALL)
    assert m, "docs/robustness.md lost its 'Sites:' list"
    return set(re.findall(r"`([a-z_]+)`", m.group(1)))


def _code_sites() -> set[str]:
    sites: set[str] = set()
    for target in _SCAN:
        path = os.path.join(_ROOT, target)
        if os.path.isfile(path):
            files = [path]
        else:
            files = [os.path.join(r, n) for r, _d, ns in os.walk(path)
                     for n in ns if n.endswith(".py")
                     and "__pycache__" not in r]
        for fp in files:
            with open(fp) as f:
                sites.update(_CALL_RE.findall(f.read()))
    return sites


class TestFaultSiteDrift:
    def test_docs_match_registry(self):
        assert _doc_sites() == set(FAULT_SITES), (
            "docs/robustness.md 'Sites:' list and "
            "resilience.FAULT_SITES disagree")

    def test_call_sites_match_registry(self):
        code = _code_sites()
        undocumented = code - set(FAULT_SITES)
        assert not undocumented, (
            f"FAULTS call sites not in FAULT_SITES: {sorted(undocumented)}"
            " — register them (and document in docs/robustness.md)")
        dead = set(FAULT_SITES) - code
        assert not dead, (
            f"FAULT_SITES entries with no call site: {sorted(dead)}"
            " — delete them (and from docs/robustness.md)")

    def test_registry_entries_described(self):
        for site, desc in FAULT_SITES.items():
            assert isinstance(desc, str) and desc, site


class TestLintCoverage:
    def test_guard_and_quarantine_paths_are_linted(self):
        """check_host_syncs.py must keep the ISSUE-4 hot paths in its
        default target list (the lint is tier-1 via
        tests/test_host_sync_lint.py — dropping a target silently
        un-guards it)."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_host_syncs",
            os.path.join(_ROOT, "tools", "check_host_syncs.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        targets = set(mod.DEFAULT_TARGETS)
        for needed in ("caffe_mpi_tpu/data/feeder.py",
                       "caffe_mpi_tpu/data/datasets.py",
                       "caffe_mpi_tpu/data/lmdb_io.py",
                       "caffe_mpi_tpu/data/leveldb_io.py",
                       "caffe_mpi_tpu/utils/resilience.py"):
            assert needed in targets, needed
