"""Doc-drift tripwires — THIN WRAPPER over the lint framework's
doc-drift pass (ISSUE 5: one enforcement path, two entry points; the
substance lives in caffe_mpi_tpu/tools/lint/doc_drift.py and is also
reachable as `python -m caffe_mpi_tpu.tools.lint --select doc-drift`).

Held equal by the pass: the `FAULT_SITES` registry in
utils/resilience.py, the `Sites:` list in docs/robustness.md, and the
literal site names at FAULTS call sites. Pure text/AST — no jax, no
device work; tier-1 cheap.
"""

import os

from caffe_mpi_tpu.tools import lint

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestFaultSiteDrift:
    def test_registry_docs_and_call_sites_agree(self):
        """The doc-drift pass holds registry == docs == call sites (and
        every registry entry described); any drift is a finding."""
        findings = lint.run_lint(paths=[], select=["doc-drift"],
                                 root=_ROOT)
        assert findings == [], "\n".join(f.format(_ROOT) for f in findings)

    def test_registry_importable_and_matches_ast_view(self):
        """The pass reads FAULT_SITES by AST (works without the package
        importable); the real import must agree with that view."""
        from caffe_mpi_tpu.tools.lint.doc_drift import (REGISTRY_FILE,
                                                        _registry_sites)
        from caffe_mpi_tpu.utils.resilience import FAULT_SITES
        sites, line = _registry_sites(os.path.join(_ROOT, REGISTRY_FILE))
        assert line > 0
        assert set(sites) == set(FAULT_SITES)
        for site, (_, desc) in sites.items():
            assert desc == FAULT_SITES[site], site


class TestLintCoverage:
    def test_hot_paths_stay_in_the_whole_tree_scan(self):
        """The framework's default scan must keep covering the ISSUE-3/4
        hot paths (they are a subset of the whole-tree roots — dropping
        a root from DEFAULT_SCAN silently un-guards them), and the
        legacy shim must keep naming them for muscle memory."""
        import importlib.util
        assert lint.DEFAULT_SCAN[0] == "caffe_mpi_tpu"
        spec = importlib.util.spec_from_file_location(
            "check_host_syncs",
            os.path.join(_ROOT, "tools", "check_host_syncs.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        targets = set(mod.DEFAULT_TARGETS)
        for needed in ("caffe_mpi_tpu/data/feeder.py",
                       "caffe_mpi_tpu/data/datasets.py",
                       "caffe_mpi_tpu/data/lmdb_io.py",
                       "caffe_mpi_tpu/data/leveldb_io.py",
                       "caffe_mpi_tpu/utils/resilience.py"):
            assert needed in targets, needed
