"""Native decode plane tests (ISSUE 10): JPEG/PNG parity vs PIL, the
fused decode->transform entry point vs the numpy reference on identical
augmentation decisions, the decoded-record cache tier, corrupt-record
quarantine through the native path, and graceful PIL fallback.

Parity contract (native/decode.cc): PNG is BITWISE equal to PIL — the
format is lossless, any correct decoder agrees. JPEG is allowed 1 LSB
per pixel: IDCT implementations may legally differ between libjpeg
builds (on this image both PIL's bundled and the system libjpeg are
turbo and agree bitwise; the contract keeps the test portable).
"""

import io
import os
import subprocess

import numpy as np
import pytest

from caffe_mpi_tpu import native
from caffe_mpi_tpu.data import DataTransformer, Feeder
from caffe_mpi_tpu.data import decode as dmod
from caffe_mpi_tpu.data.datasets import (DecodedCacheDataset,
                                         ImageFolderDataset,
                                         encode_datum_image, open_dataset)
from caffe_mpi_tpu.data.lmdb_io import write_lmdb
from caffe_mpi_tpu.proto import TransformationParameter
from caffe_mpi_tpu.utils.resilience import RecordIntegrityError

M64 = (1 << 64) - 1


def _sm64(x):
    """splitmix64 replica (transform_core.h) — the aug-decision oracle."""
    x = (x + 0x9E3779B97F4A7C15) & M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & M64
    return x ^ (x >> 31)


def _pil_chw(data):
    from PIL import Image
    img = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
    return img[:, :, ::-1].transpose(2, 0, 1)


def _encode(img_hwc_rgb, fmt, **kw):
    from PIL import Image
    b = io.BytesIO()
    Image.fromarray(img_hwc_rgb).save(b, fmt, **kw)
    return b.getvalue()


@pytest.fixture(scope="module", autouse=True)
def built():
    if not native.available():
        script = os.path.join(os.path.dirname(native.__file__), "build.sh")
        try:
            subprocess.run(["sh", script], check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            pytest.skip("native toolchain unavailable")
        native._TRIED = False  # re-probe
    if not (native.available() and native.decode_available()):
        pytest.skip("native decode plane unavailable (no libjpeg/libpng "
                    "at build time) — PIL fallback covers production")


@pytest.fixture(autouse=True)
def clean_env(monkeypatch):
    monkeypatch.delenv("CAFFE_NATIVE_DECODE", raising=False)
    dmod.STATS.reset()


class TestDecodeParity:
    def test_png_bitwise_vs_pil(self, rng):
        img = rng.randint(0, 256, (33, 47, 3)).astype(np.uint8)
        data = _encode(img, "PNG")
        assert native.decode_probe(data) == (33, 47)
        nat = native.decode_image_native(data)
        np.testing.assert_array_equal(nat, _pil_chw(data))

    def test_jpeg_within_1lsb_vs_pil(self, rng):
        img = rng.randint(0, 256, (64, 48, 3)).astype(np.uint8)
        for quality in (70, 95):
            data = _encode(img, "JPEG", quality=quality)
            nat = native.decode_image_native(data)
            pil = _pil_chw(data)
            assert nat.shape == pil.shape == (3, 64, 48)
            # IDCT variance bound — bitwise on this image (both turbo)
            assert np.abs(nat.astype(int) - pil.astype(int)).max() <= 1

    def test_gray_jpeg_and_palette_png_expand_like_pil(self, rng):
        from PIL import Image
        img = rng.randint(0, 256, (20, 24, 3)).astype(np.uint8)
        b = io.BytesIO()
        Image.fromarray(img).convert("L").save(b, "JPEG")
        gray = b.getvalue()
        assert np.abs(native.decode_image_native(gray).astype(int)
                      - _pil_chw(gray).astype(int)).max() <= 1
        b = io.BytesIO()
        Image.fromarray(img).convert(
            "P", palette=Image.ADAPTIVE).save(b, "PNG")
        pal = b.getvalue()
        np.testing.assert_array_equal(native.decode_image_native(pal),
                                      _pil_chw(pal))

    def test_unsupported_variants_decline_to_pil(self, rng):
        img = rng.randint(0, 256, (10, 10, 3)).astype(np.uint8)
        rgba = np.dstack([img, img[:, :, 0]])
        alpha_png = _encode(rgba, "PNG")
        assert native.decode_image_native(alpha_png) is None  # declines
        out = dmod.decode_image(alpha_png)  # plane falls back to PIL
        assert out.shape[0] == 3
        s = dmod.STATS.snapshot()
        assert s["native_fallbacks"] == 1 and s["pil_records"] == 1

    def test_corrupt_bytes_decline_not_crash(self, rng):
        img = rng.randint(0, 256, (16, 16, 3)).astype(np.uint8)
        bad = bytearray(_encode(img, "JPEG"))
        bad[4:40] = b"\x00" * 36
        assert native.decode_image_native(bytes(bad)) is None
        with pytest.raises(Exception):
            dmod.decode_image(bytes(bad))  # PIL also fails -> caller's
            #                                RecordIntegrityError plane

    def test_env_0_forces_pil(self, rng, monkeypatch):
        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "0")
        img = rng.randint(0, 256, (8, 8, 3)).astype(np.uint8)
        dmod.decode_image(_encode(img, "PNG"))
        s = dmod.STATS.snapshot()
        assert s["pil_records"] == 1 and s["native_records"] == 0


class TestFusedDecodeTransform:
    def _mk(self, rng, n=6, h=21, w=17):
        imgs = rng.randint(0, 256, (n, h, w, 3)).astype(np.uint8)
        return [_encode(im, "PNG") for im in imgs]

    def test_fused_bitwise_vs_numpy_test_phase(self, rng):
        """TEST phase: center crop, no RNG — the numpy DataTransformer
        applied to the (bitwise-identical) decoded pixels must match the
        fused output bit for bit."""
        bufs = self._mk(rng)
        mean = np.asarray([11.0, 22.0, 33.0], np.float32)
        out = np.empty((len(bufs), 3, 12, 12), np.float32)
        status = native.decode_transform_batch(
            bufs, np.arange(len(bufs)), crop=12, mean=mean, scale=0.125,
            train=False, mirror=False, seed=9, out_h=12, out_w=12, out=out)
        assert (status == native.DECODE_OK).all()
        tp = TransformationParameter.from_text(
            "crop_size: 12 scale: 0.125 mean_value: 11 mean_value: 22 "
            "mean_value: 33")
        tf = DataTransformer(tp, "TEST")
        ref = np.stack([tf(_pil_chw(b)) for b in bufs])
        np.testing.assert_array_equal(out, ref)

    def test_fused_bitwise_vs_numpy_train_phase(self, rng):
        """TRAIN phase: replicate the splitmix64 decisions (the SAME
        keys the classic native transform uses) and apply the reference
        float32 arithmetic in numpy — bitwise."""
        h, w, crop, seed = 21, 17, 12, 77
        bufs = self._mk(rng, h=h, w=w)
        ids = np.asarray([100, 205, 3, 44, 9999, 123456], np.int64)
        mean = np.asarray([5.0, 6.0, 7.0], np.float32)
        scale = np.float32(0.25)
        out = np.empty((len(bufs), 3, crop, crop), np.float32)
        status = native.decode_transform_batch(
            bufs, ids, crop=crop, mean=mean, scale=float(scale),
            train=True, mirror=True, seed=seed, out_h=crop, out_w=crop,
            out=out)
        assert (status == native.DECODE_OK).all()
        for k, (buf, rid) in enumerate(zip(bufs, ids)):
            img = _pil_chw(buf)
            r = _sm64(seed ^ int(rid))
            oh = r % (h - crop + 1)
            r = _sm64(r)
            ow = r % (w - crop + 1)
            r = _sm64(r)
            mir = r & 1
            ref = (img[:, oh:oh + crop, ow:ow + crop].astype(np.float32)
                   - mean[:, None, None]) * scale
            if mir:
                ref = ref[:, :, ::-1]
            np.testing.assert_array_equal(out[k], ref)

    def test_fused_equals_decode_then_transform_batch(self, rng):
        """The two native entry points share transform_core.h — same
        pixels in, bitwise-same batch out."""
        bufs = self._mk(rng, n=4)
        ids = np.arange(4, dtype=np.int64) + 31
        out = np.empty((4, 3, 10, 10), np.float32)
        status = native.decode_transform_batch(
            bufs, ids, crop=10, scale=1.0, train=True, mirror=True,
            seed=5, out_h=10, out_w=10, out=out, num_threads=3)
        assert (status == native.DECODE_OK).all()
        decoded = np.stack([native.decode_image_native(b) for b in bufs])
        ref = native.transform_batch(decoded, ids, crop=10, train=True,
                                     mirror=True, seed=5)
        np.testing.assert_array_equal(out, ref)

    def test_decode_only_mode_fills_staging(self, rng):
        bufs = self._mk(rng, n=3, h=9, w=8)
        stack = np.zeros((3, 3, 9, 8), np.uint8)
        status = native.decode_transform_batch(
            bufs, np.arange(3), out_h=9, out_w=8, out=None,
            decoded_out=[stack[i] for i in range(3)])
        assert (status == native.DECODE_OK).all()
        for i, b in enumerate(bufs):
            np.testing.assert_array_equal(stack[i], _pil_chw(b))


class TestFeederFused:
    def _db(self, tmp_path, rng, n=24, codec="png", hw=(30, 26)):
        imgs = rng.randint(0, 256, (n, 3, *hw)).astype(np.uint8)
        path = str(tmp_path / "db")
        write_lmdb(path, [(f"{i:08d}".encode(),
                           encode_datum_image(imgs[i], i % 7, codec))
                          for i in range(n)])
        return path

    def _tp(self):
        return TransformationParameter.from_text(
            "crop_size: 20 mirror: true scale: 0.5 mean_value: 1 "
            "mean_value: 2 mean_value: 3")

    def test_fused_feeder_bitwise_vs_pil_path(self, tmp_path, rng,
                                              monkeypatch):
        """PNG records (decode bitwise either way): the fused batch must
        equal the CAFFE_NATIVE_DECODE=0 (pre-ISSUE-10, PIL) batch bit
        for bit — same aug decisions, same record->slot striping."""
        path = self._db(tmp_path, rng)
        batches = {}
        for env in ("0", "1"):
            monkeypatch.setenv("CAFFE_NATIVE_DECODE", env)
            f = Feeder(open_dataset("LMDB", path),
                       DataTransformer(self._tp(), "TRAIN", seed=4),
                       batch_size=8, threads=1, shuffle=True)
            batches[env] = [f._build_batch_inner(i) for i in range(3)]
            if env == "1":
                assert f._fused_ok is True
            f.close()
        for a, b in zip(batches["0"], batches["1"]):
            np.testing.assert_array_equal(a["data"], b["data"])
            np.testing.assert_array_equal(a["label"], b["label"])

    def test_decoded_cache_epoch2_bitwise_zero_decodes(self, tmp_path,
                                                       rng, monkeypatch):
        """Epoch 2 over the cached dataset: bitwise-equal batches with
        ZERO decode calls (counter-asserted). TEST phase so the
        transform is deterministic across epochs (TRAIN augmentation
        keys on the flat index by design)."""
        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "1")
        path = self._db(tmp_path, rng)
        tp = TransformationParameter.from_text("crop_size: 20")
        ds = DecodedCacheDataset(open_dataset("LMDB", path), 64.0)
        f = Feeder(ds, DataTransformer(tp, "TEST", seed=4),
                   batch_size=8, threads=1)
        ep1 = [f._build_batch_inner(i) for i in range(3)]   # epoch 1
        s1 = dmod.STATS.snapshot()
        assert s1["decode_calls"] == 24 and s1["cache_inserts"] == 24
        ep2 = [f._build_batch_inner(i) for i in range(3, 6)]  # epoch 2
        s2 = dmod.STATS.snapshot()
        assert s2["decode_calls"] == s1["decode_calls"]  # ZERO new
        assert s2["cache_hits"] >= 24
        for a, b in zip(ep1, ep2):
            np.testing.assert_array_equal(a["data"], b["data"])
            np.testing.assert_array_equal(a["label"], b["label"])
        f.close()

    def test_corrupt_jpeg_quarantines_not_crashes(self, tmp_path, rng,
                                                  monkeypatch):
        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "1")
        n = 16
        imgs = rng.randint(0, 256, (n, 3, 24, 24)).astype(np.uint8)
        recs = [(f"{i:08d}".encode(),
                 encode_datum_image(imgs[i], i, "jpeg"))
                for i in range(n)]
        bad = bytearray(recs[5][1])
        off = bytes(bad).find(b"\xff\xd8\xff")
        bad[off + 4:off + 40] = b"\x00" * 36
        recs[5] = (recs[5][0], bytes(bad))
        path = str(tmp_path / "db")
        write_lmdb(path, recs)
        # direct read: the corrupt payload is a RecordIntegrityError
        # (native declines -> PIL fails -> quarantine signal), NOT a
        # native crash
        ds = open_dataset("LMDB", path)
        with pytest.raises(RecordIntegrityError):
            ds.get(5)
        # through the fused Feeder: record 5 is substituted by its
        # deterministic neighbor and journaled
        f = Feeder(open_dataset("LMDB", path),
                   DataTransformer(self._tp(), "TRAIN", seed=4),
                   batch_size=8, threads=1)
        f._build_batch_inner(0)
        assert 5 in f._quarantined and f._sub_cache.get(5) == 6
        f.close()

    def test_pil_fallback_when_native_absent(self, tmp_path, rng,
                                             monkeypatch):
        """Simulate an unbuilt .so: the plane reports unavailable, the
        Feeder stays classic, batches still assemble via PIL."""
        path = self._db(tmp_path, rng, n=8)
        monkeypatch.setattr(native, "_LIB", None)
        monkeypatch.setattr(native, "_TRIED", True)
        assert not dmod.native_enabled()
        f = Feeder(open_dataset("LMDB", path),
                   DataTransformer(self._tp(), "TRAIN", seed=4),
                   batch_size=8, threads=1)
        batch = f._build_batch_inner(0)
        assert batch["data"].shape == (8, 3, 20, 20)
        s = dmod.STATS.snapshot()
        assert s["pil_records"] >= 8 and s["fused_records"] == 0
        f.close()
        # forcing native with the plane absent is a loud error, not a
        # silent PIL run
        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "1")
        with pytest.raises(RuntimeError):
            dmod.native_enabled()


class TestResizeAndImageFolder:
    def test_native_bilinear_vs_numpy_reference(self, rng):
        """decode_resize vs a float64 numpy transcription of the
        cv::resize INTER_LINEAR convention (half-pixel centers, clamped
        edges, round-to-nearest) — within 1 LSB of rounding."""
        img = rng.randint(0, 256, (19, 23, 3)).astype(np.uint8)
        data = _encode(img, "PNG")
        oh, ow = 11, 29
        nat = native.decode_resize_native(data, oh, ow)
        chw = _pil_chw(data).astype(np.float64)
        h, w = chw.shape[1:]
        fy = np.clip((np.arange(oh) + 0.5) * (h / oh) - 0.5, 0, None)
        fx = np.clip((np.arange(ow) + 0.5) * (w / ow) - 0.5, 0, None)
        y0 = np.minimum(fy.astype(int), h - 1)
        x0 = np.minimum(fx.astype(int), w - 1)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (fy - y0)[None, :, None]
        wx = (fx - x0)[None, None, :]
        p00 = chw[:, y0][:, :, x0]
        p01 = chw[:, y0][:, :, x1]
        p10 = chw[:, y1][:, :, x0]
        p11 = chw[:, y1][:, :, x1]
        top = p00 + wx * (p01 - p00)
        bot = p10 + wx * (p11 - p10)
        ref = np.floor(top + wy * (bot - top) + 0.5)
        assert np.abs(nat.astype(np.float64) - ref).max() <= 1

    def test_identity_resize_is_decode(self, rng):
        img = rng.randint(0, 256, (14, 15, 3)).astype(np.uint8)
        data = _encode(img, "PNG")
        np.testing.assert_array_equal(
            native.decode_resize_native(data, 14, 15), _pil_chw(data))

    def test_image_folder_native_route(self, tmp_path, rng, monkeypatch):
        from PIL import Image
        imgs = rng.randint(0, 256, (4, 3, 18, 18)).astype(np.uint8)
        lines = []
        for i in range(4):
            p = tmp_path / f"im{i}.png"
            Image.fromarray(imgs[i].transpose(1, 2, 0)).save(str(p))
            lines.append(f"im{i}.png {i}")
        src = tmp_path / "index.txt"
        src.write_text("\n".join(lines) + "\n")
        ds = ImageFolderDataset(str(src), root=str(tmp_path))
        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "1")
        arr, label = ds.get(2)
        assert label == 2
        s = dmod.STATS.snapshot()
        assert s["native_records"] == 1
        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "0")
        ref, _ = ds.get(2)
        np.testing.assert_array_equal(arr, ref[::-1][::-1])  # both BGR CHW
        np.testing.assert_array_equal(arr, ref)
        # resize route: shape + native engagement (bilinear conventions
        # differ from PIL's antialiased BILINEAR by design — the native
        # path follows the reference's cv::resize)
        ds2 = ImageFolderDataset(str(src), root=str(tmp_path),
                                 new_height=9, new_width=12)
        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "1")
        arr2, _ = ds2.get(1)
        assert arr2.shape == (3, 9, 12)
        # grayscale stays on the PIL path (luma weights) on either env
        ds3 = ImageFolderDataset(str(src), root=str(tmp_path),
                                 is_color=False)
        g1, _ = ds3.get(0)
        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "0")
        g0, _ = ds3.get(0)
        np.testing.assert_array_equal(g1, g0)
        assert g1.shape == (1, 18, 18)
