"""Ring-attention tests on the 8-device CPU mesh: the sequence-parallel
result must match single-device attention exactly (same math, different
schedule)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.ops.attention import (
    attention,
    ring_attention,
    sequence_parallel_attention,
)
from caffe_mpi_tpu.parallel import MeshPlan


def qkv(rng, b=2, s=32, h=4, d=8):
    def mk():
        return jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    return mk(), mk(), mk()


class TestAttention:
    def test_matches_naive_softmax(self, rng):
        q, k, v = qkv(rng, s=16)
        out = attention(q, k, v)
        # naive reference
        s_ = np.einsum("bqhd,bkhd->bhqk", np.array(q), np.array(k)) / np.sqrt(8)
        p = np.exp(s_ - s_.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        expect = np.einsum("bhqk,bkhd->bqhd", p, np.array(v))
        np.testing.assert_allclose(np.array(out), expect, rtol=2e-5, atol=1e-6)

    def test_causal_masks_future(self, rng):
        q, k, v = qkv(rng, s=8)
        out = attention(q, k, v, causal=True)
        # first position attends only to itself
        expect0 = np.array(v)[:, 0]
        np.testing.assert_allclose(np.array(out)[:, 0], expect0, rtol=1e-5)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, rng, causal):
        from caffe_mpi_tpu.ops.flash_attention import flash_attention
        q, k, v = qkv(rng, b=2, s=256, h=2, d=32)
        ref = attention(q, k, v, causal=causal)
        # interpret mode on CPU; the same kernel compiles via Mosaic on TPU
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-5,
                                   atol=1e-6)

    def test_rejects_ragged_sequences(self, rng):
        from caffe_mpi_tpu.ops.flash_attention import flash_attention
        q, k, v = qkv(rng, s=130)
        with pytest.raises(ValueError, match="multiples"):
            flash_attention(q, k, v)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_device(self, rng, causal):
        plan = MeshPlan.data_parallel()  # 8 devices on 'data'
        q, k, v = qkv(rng, b=2, s=32, h=4, d=8)  # 4 seq positions per device
        ref = attention(q, k, v, causal=causal)
        out = sequence_parallel_attention(q, k, v, plan.mesh,
                                          seq_axis="data", causal=causal)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-4,
                                   atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_bigger_shapes(self, rng, causal):
        """Non-toy sizes: S=256 over 8 devices (32/shard), 8 heads, d=32."""
        plan = MeshPlan.data_parallel()
        q, k, v = qkv(rng, b=2, s=256, h=8, d=32)
        ref = attention(q, k, v, causal=causal)
        out = sequence_parallel_attention(q, k, v, plan.mesh,
                                          seq_axis="data", causal=causal)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-3,
                                   atol=1e-5)

    @pytest.mark.parametrize("s", [13, 27, 63])
    @pytest.mark.parametrize("causal", [False, True])
    def test_uneven_sequence_shards(self, rng, s, causal):
        """S not divisible by the ring size: padded up, pad keys masked in
        every block, output sliced back — results identical to the
        single-device reference."""
        plan = MeshPlan.data_parallel()  # 8 devices; 13/27/63 all uneven
        q, k, v = qkv(rng, b=2, s=s, h=2, d=8)
        ref = attention(q, k, v, causal=causal)
        out = sequence_parallel_attention(q, k, v, plan.mesh,
                                          seq_axis="data", causal=causal)
        assert out.shape == q.shape
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-4,
                                   atol=1e-5)

    def test_mixed_causal_and_not_same_program(self, rng):
        """Both mask modes through the same jitted caller (mode is a
        static argument; both variants must trace and agree)."""
        plan = MeshPlan.data_parallel()
        q, k, v = qkv(rng, b=1, s=24, h=2, d=8)

        @jax.jit
        def both(q, k, v):
            a = sequence_parallel_attention(q, k, v, plan.mesh,
                                            seq_axis="data", causal=False)
            b = sequence_parallel_attention(q, k, v, plan.mesh,
                                            seq_axis="data", causal=True)
            return a, b
        a, b = both(q, k, v)
        np.testing.assert_allclose(np.array(a),
                                   np.array(attention(q, k, v)),
                                   rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(np.array(b),
                                   np.array(attention(q, k, v, causal=True)),
                                   rtol=2e-4, atol=1e-5)

    def test_gradients_flow(self, rng):
        plan = MeshPlan.data_parallel()
        q, k, v = qkv(rng, b=1, s=16, h=2, d=4)

        def loss_ring(q, k, v):
            return jnp.sum(sequence_parallel_attention(
                q, k, v, plan.mesh, seq_axis="data"))

        def loss_ref(q, k, v):
            return jnp.sum(attention(q, k, v))

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.array(a), np.array(b), rtol=5e-4,
                                       atol=1e-5)
