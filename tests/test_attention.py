"""Ring-attention tests on the 8-device CPU mesh: the sequence-parallel
result must match single-device attention exactly (same math, different
schedule)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.ops.attention import (
    attention,
    ring_attention,
    sequence_parallel_attention,
)
from caffe_mpi_tpu.parallel import MeshPlan


def qkv(rng, b=2, s=32, h=4, d=8):
    def mk():
        return jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    return mk(), mk(), mk()


class TestAttention:
    def test_matches_naive_softmax(self, rng):
        q, k, v = qkv(rng, s=16)
        out = attention(q, k, v)
        # naive reference
        s_ = np.einsum("bqhd,bkhd->bhqk", np.array(q), np.array(k)) / np.sqrt(8)
        p = np.exp(s_ - s_.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        expect = np.einsum("bhqk,bkhd->bqhd", p, np.array(v))
        np.testing.assert_allclose(np.array(out), expect, rtol=2e-5, atol=1e-6)

    def test_causal_masks_future(self, rng):
        q, k, v = qkv(rng, s=8)
        out = attention(q, k, v, causal=True)
        # first position attends only to itself
        expect0 = np.array(v)[:, 0]
        np.testing.assert_allclose(np.array(out)[:, 0], expect0, rtol=1e-5)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, rng, causal):
        from caffe_mpi_tpu.ops.flash_attention import flash_attention
        q, k, v = qkv(rng, b=2, s=256, h=2, d=32)
        ref = attention(q, k, v, causal=causal)
        # interpret mode on CPU; the same kernel compiles via Mosaic on TPU
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-5,
                                   atol=1e-6)

    @pytest.mark.parametrize("sq,sk", [(130, 130), (300, 160), (100, 333),
                                       (257, 257)])
    @pytest.mark.parametrize("causal", [False, True])
    def test_uneven_lengths_match_reference(self, rng, sq, sk, causal):
        """Lengths that don't tile evenly are padded+masked in-kernel:
        padded key columns must not leak into the softmax denominator and
        padded query rows must not leak into dK/dV."""
        from caffe_mpi_tpu.ops.flash_attention import flash_attention
        q, _, _ = qkv(rng, b=1, s=sq, h=2, d=32)
        _, k, v = qkv(rng, b=1, s=sk, h=2, d=32)
        ref = attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-5,
                                   atol=1e-6)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal, interpret=True)
            return jnp.sum(jnp.sin(o))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(attention(q, k, v, causal=causal)))

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.array(a), np.array(b), rtol=2e-4,
                                       atol=2e-5, err_msg=f"d{name}")

    def test_uneven_lengths_extreme_logits_no_nan(self, rng):
        """With padded keys and all-strongly-negative valid scores
        (row lse < -88), the recomputed p at padded columns is
        exp(0 - lse) -> inf; unmasked it would NaN dQ via inf*0."""
        from caffe_mpi_tpu.ops.flash_attention import flash_attention
        q, _, _ = qkv(rng, b=1, s=160, h=1, d=32)
        _, k, v = qkv(rng, b=1, s=160, h=1, d=32)
        # drive every valid score strongly negative (row lse ~ -100,
        # past the exp(-lse) f32 overflow threshold of ~88.7) while
        # keeping softmax comparisons meaningful
        q = jnp.abs(q) * 6.0
        k = -jnp.abs(k) * 6.0
        g = jax.grad(lambda q: jnp.sum(flash_attention(
            q, k, v, interpret=True)))(q)
        assert np.isfinite(np.array(g)).all()
        gr = jax.grad(lambda q: jnp.sum(attention(q, k, v)))(q)
        np.testing.assert_allclose(np.array(g), np.array(gr), rtol=2e-4,
                                   atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward_matches_reference(self, rng, causal):
        """jax.grad through the Pallas kernels (custom_vjp: dQ kernel +
        dK/dV kernel, probabilities recomputed from the saved logsumexp)
        must match jax.grad through the jnp reference attention."""
        from caffe_mpi_tpu.ops.flash_attention import flash_attention
        q, k, v = qkv(rng, b=2, s=256, h=2, d=32)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal, interpret=True)
            return jnp.sum(jnp.sin(o))  # non-trivial cotangent

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(attention(q, k, v, causal=causal)))

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.array(a), np.array(b), rtol=2e-4,
                                       atol=2e-5, err_msg=f"d{name}")

    @pytest.mark.skipif(jax.default_backend() != "tpu",
                        reason="real Mosaic compile path needs a TPU")
    @pytest.mark.parametrize("causal", [False, True])
    def test_tpu_mosaic_compile_fwd_bwd(self, rng, causal):
        """On real TPU: the kernels must COMPILE via Mosaic (not
        interpret) and match the jnp reference forward and backward —
        interpret-mode tests cannot prove the TPU lowering."""
        from caffe_mpi_tpu.ops.flash_attention import flash_attention
        q, k, v = qkv(rng, b=1, s=256, h=2, d=32)
        ref = attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, interpret=False)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-3,
                                   atol=1e-4)
        g = jax.grad(lambda q: jnp.sum(
            flash_attention(q, k, v, causal=causal, interpret=False) ** 2))(q)
        gr = jax.grad(lambda q: jnp.sum(
            attention(q, k, v, causal=causal) ** 2))(q)
        np.testing.assert_allclose(np.array(g), np.array(gr), rtol=5e-3,
                                   atol=1e-4)

    def test_bf16_inputs(self, rng):
        """bf16 activations (the FLOAT16 policy) through the kernels:
        compute is f32 internally, output returns bf16, and fwd/bwd track
        the f32 reference at bf16 resolution."""
        from caffe_mpi_tpu.ops.flash_attention import flash_attention
        q, k, v = qkv(rng, b=1, s=128, h=2, d=16)
        qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
        out = flash_attention(qb, kb, vb, causal=True, interpret=True)
        assert out.dtype == jnp.bfloat16
        ref = attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.array(out, np.float32), np.array(ref),
                                   rtol=2e-2, atol=2e-2)
        g = jax.grad(lambda qb: jnp.sum(flash_attention(
            qb, kb, vb, causal=True, interpret=True).astype(jnp.float32)))(qb)
        assert g.dtype == jnp.bfloat16
        assert np.isfinite(np.array(g, np.float32)).all()

    def test_use_flash_entry_gradcheck(self, rng):
        """Finite-difference gradient check through the public
        attention(use_flash=True) entry (the framework's gradcheck bar,
        reference test_gradient_check_util.hpp)."""
        q, k, v = qkv(rng, b=1, s=128, h=1, d=8)

        def f(q):
            return jnp.sum(attention(q, k, v, use_flash=True) ** 2)

        g = jax.grad(f)(q)
        eps = 1e-3
        r = np.random.RandomState(0)
        for _ in range(5):
            idx = tuple(r.randint(0, s) for s in q.shape)
            dq = np.zeros(q.shape, np.float32)
            dq[idx] = eps
            fd = (float(f(q + dq)) - float(f(q - dq))) / (2 * eps)
            np.testing.assert_allclose(float(g[idx]), fd, rtol=2e-2,
                                       atol=1e-4)

    def test_backward_multi_tile(self, rng):
        """Sequences spanning several 128-wide tiles exercise the
        fori_loop accumulation and the causal tile-skip in both backward
        kernels."""
        from caffe_mpi_tpu.ops.flash_attention import flash_attention
        q, k, v = qkv(rng, b=1, s=384, h=1, d=16)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           interpret=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention(q, k, v, causal=True) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.array(a), np.array(b), rtol=5e-4,
                                       atol=2e-5)


class TestRingFlash:
    """Ring schedule with Pallas flash blocks (interpret mode on CPU):
    must match single-device attention exactly, forward and backward,
    including uneven lengths (global pad masked via the kernels' key
    bias) and causal block skipping.

    Tracing + interpret-mode execution of an 8-device ring program costs
    10-30 s per case on the one host core, so the heaviest variants are
    marked slow to keep tier-1 inside its wall-clock budget: where a
    causal/non-causal pair exists the causal variant (strictly more
    masking + block-skipping coverage) stays in tier-1 and the
    non-causal one goes slow; the two extreme edge-case tests
    (fully-padded shards, 1030-long multi-tile) are slow outright."""

    @pytest.mark.parametrize("causal", [
        pytest.param(False, marks=pytest.mark.slow), True])
    def test_matches_single_device(self, rng, causal):
        plan = MeshPlan.data_parallel()
        q, k, v = qkv(rng, b=1, s=64, h=2, d=16)
        ref = attention(q, k, v, causal=causal)
        out = sequence_parallel_attention(q, k, v, plan.mesh,
                                          seq_axis="data", causal=causal,
                                          use_flash=True,
                                          flash_interpret=True)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-5,
                                   atol=1e-6)

    @pytest.mark.parametrize("s", [100,
                                   pytest.param(200,
                                                marks=pytest.mark.slow)])
    @pytest.mark.parametrize("causal", [
        pytest.param(False, marks=pytest.mark.slow), True])
    def test_uneven_lengths(self, rng, s, causal):
        plan = MeshPlan.data_parallel()
        q, k, v = qkv(rng, b=1, s=s, h=2, d=16)
        ref = attention(q, k, v, causal=causal)
        out = sequence_parallel_attention(q, k, v, plan.mesh,
                                          seq_axis="data", causal=causal,
                                          use_flash=True,
                                          flash_interpret=True)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-5,
                                   atol=1e-6)

    @pytest.mark.parametrize("causal", [
        pytest.param(False, marks=pytest.mark.slow), True])
    def test_gradients_match_single_device(self, rng, causal):
        plan = MeshPlan.data_parallel()
        q, k, v = qkv(rng, b=1, s=72, h=1, d=8)  # uneven: 72 = 8*9

        def loss_ring(q, k, v):
            o = sequence_parallel_attention(q, k, v, plan.mesh,
                                            seq_axis="data", causal=causal,
                                            use_flash=True,
                                            flash_interpret=True)
            return jnp.sum(jnp.sin(o))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(attention(q, k, v, causal=causal)))

        gf = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.array(a), np.array(b), rtol=5e-4,
                                       atol=2e-5, err_msg=f"d{name}")

    @pytest.mark.slow
    def test_fully_padded_shards_with_saturated_scores(self, rng):
        """s=9 over an 8-way ring leaves shards 5-7 entirely padding; a
        fully-masked flash block's clamped lse (~ -69) must NOT enter the
        merge — with all genuine scores ~ -100 a phantom exp(-69) term
        would dominate the denominator and collapse the output to ~0."""
        plan = MeshPlan.data_parallel()
        q, _, _ = qkv(rng, b=1, s=9, h=1, d=32)
        _, k, v = qkv(rng, b=1, s=9, h=1, d=32)
        q = jnp.abs(q) * 6.0
        k = -jnp.abs(k) * 6.0
        ref = attention(q, k, v)
        out = sequence_parallel_attention(q, k, v, plan.mesh,
                                          seq_axis="data", use_flash=True,
                                          flash_interpret=True)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-5,
                                   atol=1e-6)
        gf = jax.grad(lambda q: jnp.sum(jnp.sin(sequence_parallel_attention(
            q, k, v, plan.mesh, seq_axis="data", use_flash=True,
            flash_interpret=True))))(q)
        gr = jax.grad(lambda q: jnp.sum(jnp.sin(attention(q, k, v))))(q)
        assert np.isfinite(np.array(gf)).all()
        np.testing.assert_allclose(np.array(gf), np.array(gr), rtol=5e-4,
                                   atol=2e-5)

    @pytest.mark.slow
    def test_long_local_shards_multi_tile(self, rng):
        """ceil(s/n) > 128 exercises the paths short tests can't: padding
        to n*128 multiples (s=1030 -> 2048, local shards of 256 = two
        flash tiles), the multi-tile bias dslice in every kernel, shards
        5-7 being ENTIRELY padding (their blocks merge a clamped lse),
        and the causal cross-block schedule at scale."""
        plan = MeshPlan.data_parallel()
        q, k, v = qkv(rng, b=1, s=1030, h=1, d=8)
        ref = attention(q, k, v, causal=True)
        out = sequence_parallel_attention(q, k, v, plan.mesh,
                                          seq_axis="data", causal=True,
                                          use_flash=True,
                                          flash_interpret=True)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=5e-5,
                                   atol=1e-5)
        gf = jax.grad(lambda q: jnp.sum(jnp.sin(sequence_parallel_attention(
            q, k, v, plan.mesh, seq_axis="data", causal=True,
            use_flash=True, flash_interpret=True))))(q)
        gr = jax.grad(lambda q: jnp.sum(jnp.sin(
            attention(q, k, v, causal=True))))(q)
        np.testing.assert_allclose(np.array(gf), np.array(gr), rtol=5e-4,
                                   atol=2e-5)

    def test_matches_jnp_ring(self, rng):
        # same schedule, two block implementations — cross-check
        plan = MeshPlan.data_parallel()
        q, k, v = qkv(rng, b=2, s=64, h=2, d=16)
        a = sequence_parallel_attention(q, k, v, plan.mesh, seq_axis="data",
                                        causal=True)
        b = sequence_parallel_attention(q, k, v, plan.mesh, seq_axis="data",
                                        causal=True, use_flash=True,
                                        flash_interpret=True)
        np.testing.assert_allclose(np.array(b), np.array(a), rtol=2e-5,
                                   atol=1e-6)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_device(self, rng, causal):
        plan = MeshPlan.data_parallel()  # 8 devices on 'data'
        q, k, v = qkv(rng, b=2, s=32, h=4, d=8)  # 4 seq positions per device
        ref = attention(q, k, v, causal=causal)
        out = sequence_parallel_attention(q, k, v, plan.mesh,
                                          seq_axis="data", causal=causal)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-4,
                                   atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_bigger_shapes(self, rng, causal):
        """Non-toy sizes: S=256 over 8 devices (32/shard), 8 heads, d=32."""
        plan = MeshPlan.data_parallel()
        q, k, v = qkv(rng, b=2, s=256, h=8, d=32)
        ref = attention(q, k, v, causal=causal)
        out = sequence_parallel_attention(q, k, v, plan.mesh,
                                          seq_axis="data", causal=causal)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-3,
                                   atol=1e-5)

    @pytest.mark.parametrize("s", [13, 27, 63])
    @pytest.mark.parametrize("causal", [False, True])
    def test_uneven_sequence_shards(self, rng, s, causal):
        """S not divisible by the ring size: padded up, pad keys masked in
        every block, output sliced back — results identical to the
        single-device reference."""
        plan = MeshPlan.data_parallel()  # 8 devices; 13/27/63 all uneven
        q, k, v = qkv(rng, b=2, s=s, h=2, d=8)
        ref = attention(q, k, v, causal=causal)
        out = sequence_parallel_attention(q, k, v, plan.mesh,
                                          seq_axis="data", causal=causal)
        assert out.shape == q.shape
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=2e-4,
                                   atol=1e-5)

    def test_mixed_causal_and_not_same_program(self, rng):
        """Both mask modes through the same jitted caller (mode is a
        static argument; both variants must trace and agree)."""
        plan = MeshPlan.data_parallel()
        q, k, v = qkv(rng, b=1, s=24, h=2, d=8)

        @jax.jit
        def both(q, k, v):
            a = sequence_parallel_attention(q, k, v, plan.mesh,
                                            seq_axis="data", causal=False)
            b = sequence_parallel_attention(q, k, v, plan.mesh,
                                            seq_axis="data", causal=True)
            return a, b
        a, b = both(q, k, v)
        np.testing.assert_allclose(np.array(a),
                                   np.array(attention(q, k, v)),
                                   rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(np.array(b),
                                   np.array(attention(q, k, v, causal=True)),
                                   rtol=2e-4, atol=1e-5)

    def test_gradients_flow(self, rng):
        plan = MeshPlan.data_parallel()
        q, k, v = qkv(rng, b=1, s=16, h=2, d=4)

        def loss_ring(q, k, v):
            return jnp.sum(sequence_parallel_attention(
                q, k, v, plan.mesh, seq_axis="data"))

        def loss_ref(q, k, v):
            return jnp.sum(attention(q, k, v))

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.array(a), np.array(b), rtol=5e-4,
                                       atol=1e-5)
