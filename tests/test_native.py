"""Native C++ transformer tests: builds the library if needed, checks exact
parity with the Python reference path, thread-independence, and the Feeder
integration."""

import subprocess

import numpy as np
import pytest

from caffe_mpi_tpu import native
from caffe_mpi_tpu.data import DataTransformer, Feeder, SyntheticDataset
from caffe_mpi_tpu.proto import TransformationParameter


@pytest.fixture(scope="module", autouse=True)
def built():
    if not native.available():
        import os
        script = os.path.join(os.path.dirname(native.__file__), "build.sh")
        try:
            subprocess.run(["sh", script], check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            pytest.skip("native toolchain unavailable")
        native._TRIED = False  # re-probe
        if not native.available():
            pytest.skip("native library failed to load")


class TestNativeTransform:
    def test_test_phase_matches_python(self, rng):
        imgs = rng.randint(0, 256, (6, 3, 14, 14)).astype(np.uint8)
        tp = TransformationParameter.from_text(
            "crop_size: 10 scale: 0.25 mean_value: 5 mean_value: 6 mean_value: 7")
        tf = DataTransformer(tp, "TEST")
        ref = np.stack([tf(im) for im in imgs])
        out = native.transform_batch(
            imgs, np.arange(6), crop=10,
            mean=np.array([5.0, 6.0, 7.0], np.float32), scale=0.25,
            train=False)
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_full_mean_matches_python(self, rng, tmp_path):
        from caffe_mpi_tpu.io import save_blob_binaryproto
        imgs = rng.randint(0, 256, (4, 1, 9, 9)).astype(np.uint8)
        mean = rng.rand(1, 9, 9).astype(np.float32) * 100
        mp = str(tmp_path / "m.binaryproto")
        save_blob_binaryproto(mp, mean)
        tp = TransformationParameter.from_text(
            f'crop_size: 6 mean_file: "{mp}"')
        tf = DataTransformer(tp, "TEST")
        ref = np.stack([tf(im) for im in imgs])
        out = native.transform_batch(imgs, np.arange(4), crop=6, mean=mean,
                                     train=False)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-4)

    def test_train_determinism_and_variety(self, rng):
        imgs = rng.randint(0, 256, (16, 3, 12, 12)).astype(np.uint8)
        ids = np.arange(16)
        a = native.transform_batch(imgs, ids, crop=8, train=True, mirror=True,
                                   seed=9, num_threads=4)
        b = native.transform_batch(imgs, ids, crop=8, train=True, mirror=True,
                                   seed=9, num_threads=1)
        np.testing.assert_array_equal(a, b)
        c = native.transform_batch(imgs, ids, crop=8, train=True, mirror=True,
                                   seed=10)
        assert not np.array_equal(a, c)  # different seed, different crops

    def test_native_datumdb_reader(self, rng, tmp_path):
        from caffe_mpi_tpu.data.datasets import (DatumFileDataset,
                                                 encode_datum, open_dataset)
        recs = [(rng.randint(0, 256, (3, 5, 6)).astype(np.uint8), i % 4)
                for i in range(8)]
        path = str(tmp_path / "t.datumdb")
        DatumFileDataset.write(path, (encode_datum(a, l) for a, l in recs))
        db = native.NativeDatumDB(path)
        assert len(db) == 8
        for i, (a, l) in enumerate(recs):
            got, lab = db.get(i)
            np.testing.assert_array_equal(got, a)
            assert lab == l
        db.close()
        ds = open_dataset("DATUMFILE", path)
        got, lab = ds.get(3)
        np.testing.assert_array_equal(got, recs[3][0])

    def test_native_lmdb_matches_python_reader(self, rng, tmp_path):
        """The C++ LMDB cursor (lmdb_reader.cc) must agree record-for-
        record with the pure-Python reader (lmdb_io.py, the behavioral
        reference), including F_BIGDATA overflow values."""
        from caffe_mpi_tpu import native
        from caffe_mpi_tpu.data.lmdb_io import LMDBReader, write_lmdb
        if not native.available():
            pytest.skip("native library not built")
        items = [(f"{i:08d}".encode(),
                  rng.bytes(50 if i % 7 else 5000))  # some overflow values
                 for i in range(400)]
        path = str(tmp_path / "db")
        write_lmdb(path, items)
        nat = native.NativeLMDB(path)
        with LMDBReader(path) as py:
            assert len(nat) == len(py) == 400
            py_items = list(py.items())
            for i in range(400):
                assert nat.record(i) == py_items[i], i
        nat.close()

    def test_native_lmdb_dataset_path(self, rng, tmp_path):
        """LMDBDataset routes through the native cursor when the lmdb
        module is absent and the .so is built."""
        from caffe_mpi_tpu import native
        from caffe_mpi_tpu.data.datasets import LMDBDataset, encode_datum
        from caffe_mpi_tpu.data.lmdb_io import write_lmdb
        if not native.available():
            pytest.skip("native library not built")
        imgs = rng.randint(0, 256, (6, 3, 4, 4)).astype(np.uint8)
        path = str(tmp_path / "db")
        write_lmdb(path, [(f"{i:08d}".encode(), encode_datum(imgs[i], i))
                          for i in range(6)])
        ds = LMDBDataset(path)
        assert ds._native is not None  # native path engaged
        for i in range(6):
            arr, lab = ds.get(i)
            np.testing.assert_array_equal(arr, imgs[i])
            assert lab == i

    def test_feeder_uses_native(self, rng):
        ds = SyntheticDataset(64, shape=(3, 16, 16))
        tp = TransformationParameter.from_text(
            "crop_size: 12 scale: 0.0039 mirror: true")
        tf = DataTransformer(tp, "TRAIN", seed=4)
        feeder = Feeder(ds, tf, batch_size=8, threads=2)
        assert feeder._native
        batch = feeder(0)
        assert batch["data"].shape == (8, 3, 12, 12)
        assert batch["data"].dtype == np.float32
        batch2 = Feeder(ds, tf, batch_size=8, threads=1)(0)
        np.testing.assert_array_equal(batch["data"], batch2["data"])
        feeder.close()
