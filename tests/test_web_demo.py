"""The web demo serves real HTTP with no third-party deps (reference
examples/web_demo/app.py ran on Flask+Tornado; here stdlib http.server,
so it actually runs in this image)."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import caffe_mpi_tpu.pycaffe as caffe


@pytest.fixture(scope="module")
def demo_server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("webdemo")
    model = tmp / "deploy.prototxt"
    model.write_text("""
    name: "toy"
    layer { name: "data" type: "Input" top: "data"
            input_param { shape { dim: 1 dim: 3 dim: 8 dim: 8 } } }
    layer { name: "ip" type: "InnerProduct" bottom: "data" top: "score"
            inner_product_param { num_output: 5
              weight_filler { type: "xavier" } } }
    layer { name: "prob" type: "Softmax" bottom: "score" top: "prob" }
    """)
    net = caffe.Net(str(model), caffe.TEST)
    weights = str(tmp / "w.caffemodel")
    net.save(weights)
    labels = tmp / "labels.txt"
    labels.write_text("\n".join(f"class_{i}" for i in range(5)))

    # an image to serve via /classify_path
    from PIL import Image
    img = Image.fromarray(
        np.random.RandomState(0).randint(0, 255, (12, 12, 3), np.uint8))
    img.save(tmp / "cat.png")

    import importlib.util
    import os
    app_py = os.path.join(os.path.dirname(__file__), "..",
                          "examples", "web_demo", "app.py")
    spec = importlib.util.spec_from_file_location("web_demo_app", app_py)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    srv = mod.make_server(str(model), weights, str(labels),
                          image_root=str(tmp), port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", tmp
    srv.shutdown()


def _png_bytes():
    import io
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(np.random.RandomState(1).randint(
        0, 255, (10, 10, 3), np.uint8)).save(buf, format="PNG")
    return buf.getvalue()


def test_index_form(demo_server):
    base, _ = demo_server
    html = urllib.request.urlopen(base + "/").read()
    assert b"multipart/form-data" in html


def test_classify_raw_post(demo_server):
    base, _ = demo_server
    req = urllib.request.Request(base + "/classify", data=_png_bytes(),
                                 headers={"Content-Type": "image/png"})
    out = json.loads(urllib.request.urlopen(req).read())
    preds = out["predictions"]
    assert len(preds) == 5
    assert abs(sum(p["score"] for p in preds) - 1.0) < 1e-3
    assert preds[0]["label"].startswith("class_")
    scores = [p["score"] for p in preds]
    assert scores == sorted(scores, reverse=True)


def test_classify_multipart_post(demo_server):
    base, _ = demo_server
    boundary = "xyzzy42"
    body = (f"--{boundary}\r\n"
            'Content-Disposition: form-data; name="image"; '
            'filename="a.png"\r\n'
            "Content-Type: image/png\r\n\r\n").encode() + _png_bytes() + \
        f"\r\n--{boundary}--\r\n".encode()
    req = urllib.request.Request(
        base + "/classify", data=body,
        headers={"Content-Type":
                 f"multipart/form-data; boundary={boundary}"})
    out = json.loads(urllib.request.urlopen(req).read())
    assert len(out["predictions"]) == 5


def test_multipart_extra_field_before_image(demo_server):
    # a text form field ahead of the file part must not be mistaken for
    # the image (extraction selects the part named "image")
    base, _ = demo_server
    boundary = "xyzzy43"
    body = (f"--{boundary}\r\n"
            'Content-Disposition: form-data; name="note"\r\n\r\n'
            "hello\r\n"
            f"--{boundary}\r\n"
            'Content-Disposition: form-data; name="image"; '
            'filename="a.png"\r\n'
            "Content-Type: image/png\r\n\r\n").encode() + _png_bytes() + \
        f"\r\n--{boundary}--\r\n".encode()
    req = urllib.request.Request(
        base + "/classify", data=body,
        headers={"Content-Type":
                 f"multipart/form-data; boundary={boundary}"})
    out = json.loads(urllib.request.urlopen(req).read())
    assert len(out["predictions"]) == 5


def test_classify_path_non_image_is_400(demo_server):
    base, tmp = demo_server
    (tmp / "notes.txt").write_text("not an image")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(base + "/classify_path?path=notes.txt")
    assert e.value.code == 400


def test_classify_path_and_traversal_guard(demo_server):
    base, tmp = demo_server
    out = json.loads(urllib.request.urlopen(
        base + "/classify_path?path=cat.png").read())
    assert len(out["predictions"]) == 5
    # escaping the image root is refused
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(base + "/classify_path?path=../../etc/passwd")
    assert e.value.code == 403


def test_bad_upload_is_400(demo_server):
    base, _ = demo_server
    req = urllib.request.Request(base + "/classify", data=b"not an image",
                                 headers={"Content-Type": "image/png"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req)
    assert e.value.code == 400
