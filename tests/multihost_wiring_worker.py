"""Subprocess target for the 2-process cluster WIRING smoke (ISSUE 11).

Run as: python multihost_wiring_worker.py <coordinator> <world> <rank> \
            <workdir>

This jaxlib's CPU backend cannot form multiprocess computations
(test_multihost.py), so the wiring facts are asserted WITHOUT placing
any global array: cluster formation through the hardened
`init_distributed`, global mesh SHAPE, the Feeder's disjoint per-host
record striping over a real LMDB (observed indices exchanged through
the coordination-service KV store — the same channel the heartbeat
uses), per-host quarantine journals under injected record corruption,
and rank 0's snapshot-time merge. Rank 0 prints WIRING-OK last; the
parent (tests/test_multihost.py) asserts it.
"""

import json
import os
import sys

# one process = one simulated single-device host
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, os.pardir))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from caffe_mpi_tpu.parallel import MeshPlan  # noqa: E402
from caffe_mpi_tpu.parallel.mesh import (  # noqa: E402
    KVBeatTransport, cluster_barrier, cluster_kv_get, cluster_kv_set,
    init_distributed)
from caffe_mpi_tpu.utils import resilience  # noqa: E402

BATCH, N_RECORDS, N_ITERS = 4, 16, 2


def observed_stripe(workdir: str, rank: int, world: int) -> list[int]:
    """Build N_ITERS batches through the real Feeder and read back
    WHICH records landed in them (each record's pixels encode its
    index). The injected `record_corrupt` site (one index inside this
    rank's stripe, set by the parent) quarantines deterministically on
    the way — substitute indices are what the stripe then contains."""
    from caffe_mpi_tpu.data.datasets import LMDBDataset
    from caffe_mpi_tpu.data.feeder import Feeder
    ds = LMDBDataset(os.path.join(workdir, "db"))
    feeder = Feeder(ds, None, BATCH, rank=rank, world=world, threads=1)
    seen = []
    try:
        for it in range(N_ITERS):
            batch = feeder._build_batch_inner(it)
            seen.extend(int(v) for v in
                        np.asarray(batch["data"])[:, 0, 0, 0])
    finally:
        feeder.close()
    return seen


def main() -> None:
    coordinator, world, rank, workdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    init_distributed(coordinator, world, rank, attempts=2, timeout_s=30)

    # -- cluster facts: the mesh spans processes ----------------------
    assert jax.process_count() == world, jax.process_count()
    assert jax.process_index() == rank, jax.process_index()
    assert len(jax.devices()) == world, len(jax.devices())
    plan = MeshPlan.data_parallel()
    assert dict(plan.mesh.shape) == {"data": world, "model": 1}, \
        plan.mesh.shape

    # -- per-host record striping + quarantine journaling -------------
    prefix = os.path.join(workdir, "run", "s")
    resilience.QUARANTINE.configure(
        resilience.quarantine_journal_path(prefix, rank, world))
    stripe = observed_stripe(workdir, rank, world)
    # the reference's round-robin striping at host granularity
    # (data_reader.hpp:28-53): it*B*world + rank*B + slot — except where
    # the injected corrupt record was substituted by its next healthy
    # neighbor (a pure function of the record index)
    corrupt = int(os.environ["WIRING_CORRUPT_INDEX"])
    expected = []
    for it in range(N_ITERS):
        for slot in range(BATCH):
            flat = (it * BATCH * world + rank * BATCH + slot) % N_RECORDS
            expected.append(flat + 1 if flat == corrupt else flat)
    assert stripe == expected, (stripe, expected)
    assert resilience.QUARANTINE.count() == 1
    resilience.QUARANTINE.flush()

    # -- KV heartbeat transport works cross-process -------------------
    import time
    hb = KVBeatTransport()
    hb.publish(rank, 0)
    peer = (rank + 1) % world
    deadline = time.monotonic() + 15
    while hb.latest_seq(peer) < 0:
        assert time.monotonic() < deadline, f"no beat from host {peer}"
        time.sleep(0.05)

    # exchange observed stripes over the same KV store; rank 0 asserts
    # global disjointness + exhaustiveness
    cluster_kv_set(f"wiring/stripe/{rank}", json.dumps(stripe))
    assert cluster_barrier("wiring_journals", 30.0)
    if rank == 0:
        stripes = {r: json.loads(cluster_kv_get(f"wiring/stripe/{r}", 30.0))
                   for r in range(world)}
        raw = {r: [(it * BATCH * world + r * BATCH + s) % N_RECORDS
                   for it in range(N_ITERS) for s in range(BATCH)]
               for r in range(world)}
        flat_all = [i for r in sorted(raw) for i in raw[r]]
        assert len(set(flat_all)) == len(flat_all) == N_RECORDS, \
            "per-host stripes must be disjoint and exhaustive"
        assert all(stripes[r] is not None for r in stripes)
        # rank 0 merges the per-host quarantine journals (what the
        # solver does at snapshot time) and both hosts' entries land
        n = resilience.merge_quarantine_journals(prefix)
        merged = json.load(open(prefix + ".quarantine.json"))
        indices = sorted(e["index"] for e in merged["records"])
        both = sorted({int(os.environ["WIRING_CORRUPT_INDEX"]),
                       int(os.environ["WIRING_PEER_CORRUPT_INDEX"])})
        assert n == 2 and indices == both, (n, indices, both)
    assert cluster_barrier("wiring_done", 30.0)
    jax.distributed.shutdown()
    print(f"proc {rank}: WIRING-OK")


if __name__ == "__main__":
    main()
