"""NetSpec (programmatic model authoring) tests — pycaffe net_spec parity."""

import pytest

from caffe_mpi_tpu.net import Net
from caffe_mpi_tpu.net_spec import L, NetSpec
from caffe_mpi_tpu.proto import NetParameter


class TestNetSpec:
    def test_basic_roundtrip(self):
        n = NetSpec("tiny")
        n.data = L.Input(input_param=dict(shape=dict(dim=[4, 3, 8, 8])))
        n.conv = L.Convolution(n.data, num_output=2, kernel_size=3,
                               weight_filler=dict(type="xavier"))
        n.relu = L.ReLU(n.conv, in_place=True)
        n.pool = L.Pooling(n.relu, pool="MAX", kernel_size=2, stride=2)
        net = Net(NetParameter.from_text(n.to_prototxt()), phase="TRAIN")
        assert [l.lp.type for l in net.layers] == [
            "Input", "Convolution", "ReLU", "Pooling"]
        # in-place: ReLU reads and writes blob "conv"
        relu = net.layers[2].lp
        assert relu.bottom == ["conv"] and relu.top == ["conv"]
        assert net.blob_shapes["pool"] == (4, 2, 3, 3)

    def test_multi_top(self):
        n = NetSpec()
        n.data, n.label = L.Input(ntop=2, input_param=dict(
            shape=[dict(dim=[2, 4]), dict(dim=[2])]))
        n.sm = L.Softmax(n.data)
        txt = n.to_prototxt()
        net = NetParameter.from_text(txt)
        assert net.layer[0].top == ["data", "label"]

    def test_unassigned_inplace_layer_errors(self):
        n = NetSpec()
        n.data = L.Input(input_param=dict(shape=dict(dim=[2, 4])))
        n.ip = L.InnerProduct(n.data, num_output=3)
        L.ReLU(n.ip, in_place=True)  # discarded — must be caught
        with pytest.raises(ValueError, match="not reachable"):
            n.to_prototxt()

    def test_zero_top_layer(self):
        n = NetSpec()
        n.data, n.label = L.Input(ntop=2, input_param=dict(
            shape=[dict(dim=[2, 4]), dict(dim=[2])]))
        n.silence = L.Silence(n.label, ntop=0)
        txt = n.to_prototxt()
        net = NetParameter.from_text(txt)
        sil = [l for l in net.layer if l.type == "Silence"][0]
        assert sil.bottom == ["label"] and sil.top == []
        assert sil.name == "silence"

    def test_generated_zoo_has_activations(self):
        """Regression: generators must not silently drop in-place layers."""
        import os
        for name, min_relus in [("alexnet", 7), ("googlenet", 50),
                                ("resnet50", 45), ("cifar10_quick", 3),
                                ("caffenet", 7), ("vgg16", 15),
                                ("resnet18", 16)]:
            path = f"models/{name}/train_val.prototxt"
            if not os.path.exists(path):
                pytest.skip("models not generated")
            net = NetParameter.from_file(path)
            relus = sum(1 for l in net.layer if l.type == "ReLU")
            assert relus >= min_relus, f"{name}: only {relus} ReLUs"
