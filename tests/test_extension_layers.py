"""Extension layer tests: Python layer (pure_callback), Filter, HDF5Output,
Parameter, debug_info."""

import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.net import Net
from caffe_mpi_tpu.proto import NetParameter
from gradcheck import make_layer


# user python layer module (importable as this test module)
class DoubleLayer:
    """Example user layer: y = 2x, numpy on host."""

    def infer_shapes(self, bottom_shapes):
        return [bottom_shapes[0]]

    def forward(self, bottoms):
        return [2.0 * bottoms[0]]


class TestPythonLayer:
    def test_forward_through_callback(self, rng):
        net = Net(NetParameter.from_text("""
        layer { name: "in" type: "Input" top: "x"
                input_param { shape { dim: 2 dim: 3 } } }
        layer { name: "py" type: "Python" bottom: "x" top: "y"
                python_param { module: "test_extension_layers"
                               layer: "DoubleLayer" } }
        """))
        params, state = net.init(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(2, 3).astype(np.float32))
        # works inside jit: pure_callback stages a host call
        fwd = jax.jit(lambda p, s, f: net.apply(p, s, f, train=False)[0])
        blobs = fwd(params, state, {"x": x})
        np.testing.assert_allclose(np.array(blobs["y"]), 2 * np.array(x),
                                   rtol=1e-6)


class TestFilter:
    def test_masks_filtered_items(self, rng):
        layer, params, state = make_layer(
            'name: "f" type: "Filter" bottom: "x" bottom: "sel" top: "y"',
            [(4, 3), (4,)],
        )
        x = jnp.asarray(rng.randn(4, 3).astype(np.float32))
        sel = jnp.asarray([1.0, 0.0, 1.0, 0.0])
        (y,), _ = layer.apply(params, state, [x, sel], train=False, rng=None)
        np.testing.assert_array_equal(np.array(y)[1], 0)
        np.testing.assert_array_equal(np.array(y)[0], np.array(x)[0])


class TestHDF5Output:
    def test_writes_batches(self, rng, tmp_path):
        import h5py
        out = str(tmp_path / "acts.h5")
        net = Net(NetParameter.from_text(f"""
        layer {{ name: "in" type: "Input" top: "x" top: "lab"
                input_param {{ shape {{ dim: 2 dim: 3 }} shape {{ dim: 2 }} }} }}
        layer {{ name: "out" type: "HDF5Output" bottom: "x" bottom: "lab"
                hdf5_output_param {{ file_name: "{out}" }} }}
        """))
        params, state = net.init(jax.random.PRNGKey(0))
        x = rng.randn(2, 3).astype(np.float32)
        net.apply(params, state, {"x": jnp.asarray(x),
                                  "lab": jnp.asarray([1, 2])}, train=False)
        jax.effects_barrier()
        with h5py.File(out) as f:
            np.testing.assert_allclose(f["batch_0/data"][:], x, rtol=1e-6)
            np.testing.assert_array_equal(f["batch_0/label"][:], [1, 2])


class TestParameter:
    def test_learnable_top(self):
        net = Net(NetParameter.from_text("""
        layer { name: "p" type: "Parameter" top: "w"
                parameter_param { shape { dim: 2 dim: 3 } } }
        """))
        params, state = net.init(jax.random.PRNGKey(0))
        assert params["p"]["weight"].shape == (2, 3)
        blobs, _, _ = net.apply(params, state, {}, train=False)
        assert blobs["w"].shape == (2, 3)
