"""Extension layer tests: Python layer (pure_callback), Filter, HDF5Output,
Parameter, debug_info."""

import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.net import Net
from caffe_mpi_tpu.proto import NetParameter
from gradcheck import make_layer


# user python layer module (importable as this test module)
class DoubleLayer:
    """Example user layer: y = 2x, numpy on host."""

    def infer_shapes(self, bottom_shapes):
        return [bottom_shapes[0]]

    def forward(self, bottoms):
        return [2.0 * bottoms[0]]


class SquareLayer:
    """User layer WITH backward: y = x^2, dx = 2x * dy (the reference's
    python_layer Backward protocol, numpy on host)."""

    def infer_shapes(self, bottom_shapes):
        return [bottom_shapes[0]]

    def forward(self, bottoms):
        return [bottoms[0] ** 2]

    def backward(self, top_diffs, bottoms):
        return [2.0 * bottoms[0] * top_diffs[0]]


class TestPythonLayer:
    def test_forward_through_callback(self, rng):
        net = Net(NetParameter.from_text("""
        layer { name: "in" type: "Input" top: "x"
                input_param { shape { dim: 2 dim: 3 } } }
        layer { name: "py" type: "Python" bottom: "x" top: "y"
                python_param { module: "test_extension_layers"
                               layer: "DoubleLayer" } }
        """))
        params, state = net.init(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(2, 3).astype(np.float32))
        # works inside jit: pure_callback stages a host call
        fwd = jax.jit(lambda p, s, f: net.apply(p, s, f, train=False)[0])
        blobs = fwd(params, state, {"x": x})
        np.testing.assert_allclose(np.array(blobs["y"]), 2 * np.array(x),
                                   rtol=1e-6)


class TestPythonLayerBackward:
    NET = """
    layer { name: "in" type: "Input" top: "x"
            input_param { shape { dim: 2 dim: 3 } } }
    layer { name: "py" type: "Python" bottom: "x" top: "y"
            python_param { module: "test_extension_layers"
                           layer: "SquareLayer" } }
    """

    def test_user_backward_is_custom_vjp(self, rng):
        """jax.grad through the Python layer calls the user's numpy
        backward (spliced in as a custom VJP through pure_callback)."""
        net = Net(NetParameter.from_text(self.NET))
        params, state = net.init(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(2, 3).astype(np.float32))

        def loss(x):
            blobs, _, _ = net.apply(params, state, {"x": x}, train=True)
            return jnp.sum(blobs["y"] * jnp.arange(1.0, 7.0).reshape(2, 3))

        g = jax.grad(loss)(x)
        # d/dx sum(w * x^2) = 2 w x
        expect = 2 * np.arange(1.0, 7.0).reshape(2, 3) * np.array(x)
        np.testing.assert_allclose(np.array(g), expect, rtol=1e-5)

    def test_no_backward_stops_gradient(self, rng):
        net = Net(NetParameter.from_text(self.NET.replace(
            "SquareLayer", "DoubleLayer")))
        params, state = net.init(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(2, 3).astype(np.float32))
        g = jax.grad(lambda x: jnp.sum(
            net.apply(params, state, {"x": x}, train=True)[0]["y"]))(x)
        np.testing.assert_array_equal(np.array(g), 0.0)


class TestFilter:
    def test_masks_filtered_items(self, rng):
        layer, params, state = make_layer(
            'name: "f" type: "Filter" bottom: "x" bottom: "sel" top: "y"',
            [(4, 3), (4,)],
        )
        x = jnp.asarray(rng.randn(4, 3).astype(np.float32))
        sel = jnp.asarray([1.0, 0.0, 1.0, 0.0])
        (y,), _ = layer.apply(params, state, [x, sel], train=False, rng=None)
        np.testing.assert_array_equal(np.array(y)[1], 0)
        np.testing.assert_array_equal(np.array(y)[0], np.array(x)[0])


class TestHDF5Output:
    def test_writes_batches(self, rng, tmp_path):
        import h5py
        out = str(tmp_path / "acts.h5")
        net = Net(NetParameter.from_text(f"""
        layer {{ name: "in" type: "Input" top: "x" top: "lab"
                input_param {{ shape {{ dim: 2 dim: 3 }} shape {{ dim: 2 }} }} }}
        layer {{ name: "out" type: "HDF5Output" bottom: "x" bottom: "lab"
                hdf5_output_param {{ file_name: "{out}" }} }}
        """))
        params, state = net.init(jax.random.PRNGKey(0))
        x = rng.randn(2, 3).astype(np.float32)
        net.apply(params, state, {"x": jnp.asarray(x),
                                  "lab": jnp.asarray([1, 2])}, train=False)
        jax.effects_barrier()
        with h5py.File(out) as f:
            np.testing.assert_allclose(f["batch_0/data"][:], x, rtol=1e-6)
            np.testing.assert_array_equal(f["batch_0/label"][:], [1, 2])


class TestParameter:
    def test_learnable_top(self):
        net = Net(NetParameter.from_text("""
        layer { name: "p" type: "Parameter" top: "w"
                parameter_param { shape { dim: 2 dim: 3 } } }
        """))
        params, state = net.init(jax.random.PRNGKey(0))
        assert params["p"]["weight"].shape == (2, 3)
        blobs, _, _ = net.apply(params, state, {}, train=False)
        assert blobs["w"].shape == (2, 3)
