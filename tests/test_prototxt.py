"""Config-layer tests: text-format parsing, schema coercion, net filtering.

Mirrors the coverage of the reference's test_upgrade_proto.cpp and the
net-filtering parts of test_net.cpp.
"""

import math

import pytest

from caffe_mpi_tpu.proto import (
    NetParameter,
    NetState,
    PrototxtError,
    SolverParameter,
    filter_net,
    normalize_net,
    parse,
    solver_type,
)


LENET = """
name: "LeNet"
layer {
  name: "data" type: "Input" top: "data"
  input_param { shape { dim: 64 dim: 1 dim: 28 dim: 28 } }
}
layer {
  name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  param { lr_mult: 1 } param { lr_mult: 2 }
  convolution_param {
    num_output: 20 kernel_size: 5 stride: 1
    weight_filler { type: "xavier" }
    bias_filler { type: "constant" }
  }
}
layer {
  name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "ip1" type: "InnerProduct" bottom: "pool1" top: "ip1"
  inner_product_param { num_output: 500 weight_filler { type: "xavier" } }
}
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer {
  name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
  inner_product_param { num_output: 10 weight_filler { type: "xavier" } }
}
layer {
  name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" bottom: "label" top: "loss"
  include { phase: TRAIN }
}
layer {
  name: "accuracy" type: "Accuracy" bottom: "ip2" bottom: "label" top: "accuracy"
  include { phase: TEST }
}
"""


class TestTextFormat:
    def test_scalars(self):
        node = parse('a: 1 b: -2.5 c: 1e-3 d: true e: false f: "hi" g: FOO h: 0x10')
        assert node.get("a") == 1
        assert node.get("b") == -2.5
        assert node.get("c") == pytest.approx(1e-3)
        assert node.get("d") is True
        assert node.get("e") is False
        assert node.get("f") == "hi"
        assert node.get("g") == "FOO"
        assert node.get("h") == 16

    def test_inf_nan(self):
        node = parse("a: inf b: -inf c: nan")
        assert node.get("a") == math.inf
        assert node.get("b") == -math.inf
        assert math.isnan(node.get("c"))

    def test_string_escapes_and_concat(self):
        node = parse(r'''s: "a\n\"b\"" t: "one" "two" u: 'sq'
        ''')
        assert node.get("s") == 'a\n"b"'
        assert node.get("t") == "onetwo"
        assert node.get("u") == "sq"

    def test_inf_prefixed_identifiers(self):
        # field names starting with inf/nan must not split mid-word
        node = parse('infogain_loss_param { source: "m.binaryproto" } nano: 3')
        assert node.get("infogain_loss_param").get("source") == "m.binaryproto"
        assert node.get("nano") == 3

    def test_octal_and_hex_escapes(self):
        node = parse(r's: "\101\102\x43\0"')
        assert node.get("s") == "ABC\0"

    def test_comments(self):
        node = parse("# header\na: 1 # trailing\nb: 2")
        assert node.get("a") == 1 and node.get("b") == 2

    def test_repeated_and_lists(self):
        node = parse("dim: 1 dim: 2 dim: 3 xs: [4, 5, 6]")
        assert node.get_list("dim") == [1, 2, 3]
        assert node.get_list("xs") == [4, 5, 6]

    def test_nested_and_colon_brace(self):
        node = parse("m { x: 1 } n: { y: 2 } o < z: 3 >")
        assert node.get("m").get("x") == 1
        assert node.get("n").get("y") == 2
        assert node.get("o").get("z") == 3

    def test_errors(self):
        with pytest.raises(PrototxtError):
            parse("a: ")
        with pytest.raises(PrototxtError):
            parse("a { b: 1")
        with pytest.raises(PrototxtError):
            parse("{ }")

    def test_roundtrip(self):
        node = parse(LENET)
        again = parse(node.to_text())
        assert again.to_text() == node.to_text()
        assert len(again.get_list("layer")) == 8


class TestSchema:
    def test_lenet_coercion(self):
        net = NetParameter.from_text(LENET)
        assert net.name == "LeNet"
        assert len(net.layer) == 8
        conv = net.layer[1]
        assert conv.type == "Convolution"
        assert conv.convolution_param.num_output == 20
        assert conv.convolution_param.kernel_size == [5]
        assert conv.convolution_param.weight_filler.type == "xavier"
        assert [p.lr_mult for p in conv.param] == [1.0, 2.0]
        pool = net.layer[2]
        assert pool.pooling_param.pool == "MAX"
        assert pool.pooling_param.kernel_size == 2

    def test_unknown_fields_tolerated(self):
        net = NetParameter.from_text('name: "x" frobnicate: 7 layer { type: "ReLU" }')
        assert net.name == "x"
        assert "frobnicate" in net.unknown_fields

    def test_presence(self):
        net = NetParameter.from_text('name: "x"')
        assert net.has("name") and not net.has("force_backward")

    def test_solver(self):
        sp = SolverParameter.from_text(
            """
            net: "train.prototxt"
            base_lr: 0.01 momentum: 0.9 weight_decay: 0.0005
            lr_policy: "inv" gamma: 0.0001 power: 0.75
            max_iter: 10000 snapshot: 5000 snapshot_prefix: "lenet"
            test_iter: 100 test_interval: 500
            solver_mode: GPU type: "SGD"
            """
        )
        assert sp.base_lr == pytest.approx(0.01)
        assert sp.lr_policy == "inv"
        assert sp.test_iter == [100]
        assert solver_type(sp) == "SGD"

    def test_legacy_solver_type_enum(self):
        sp = SolverParameter.from_text("solver_type: ADAM")
        assert solver_type(sp) == "Adam"
        sp2 = SolverParameter.from_text("solver_type: 1")
        assert solver_type(sp2) == "Nesterov"

    def test_mixed_precision_fields(self):
        net = NetParameter.from_text(
            'default_forward_type: FLOAT16 default_backward_type: FLOAT16\n'
            'global_grad_scale: 1000\n'
            'layer { name: "c" type: "Convolution" forward_type: FLOAT }'
        )
        assert net.default_forward_type == "FLOAT16"
        assert net.global_grad_scale == 1000
        assert net.layer[0].forward_type == "FLOAT"


class TestFiltering:
    def test_phase_rules(self):
        net = normalize_net(NetParameter.from_text(LENET))
        train = filter_net(net, NetState(phase="TRAIN"))
        test = filter_net(net, NetState(phase="TEST"))
        train_names = [l.name for l in train.layer]
        test_names = [l.name for l in test.layer]
        assert "loss" in train_names and "accuracy" not in train_names
        assert "accuracy" in test_names and "loss" not in test_names

    def test_stage_and_level(self):
        net = NetParameter.from_text(
            """
            layer { name: "a" type: "ReLU" include { stage: "deploy" } }
            layer { name: "b" type: "ReLU" exclude { stage: "deploy" } }
            layer { name: "c" type: "ReLU" include { min_level: 1 } }
            layer { name: "d" type: "ReLU" }
            """
        )
        st = NetState(phase="TEST", stage=["deploy"], level=0)
        names = [l.name for l in filter_net(net, st).layer]
        assert names == ["a", "d"]
        st2 = NetState(phase="TEST", level=2)
        names2 = [l.name for l in filter_net(net, st2).layer]
        assert names2 == ["b", "c", "d"]

    def test_phase_field_is_not_a_filter(self):
        # reference net.cpp:125-127: layer `phase` is inherited, not a rule
        net = NetParameter.from_text(
            'layer { name: "a" type: "ReLU" phase: TRAIN exclude { stage: "x" } }'
        )
        assert [l.name for l in filter_net(net, NetState(phase="TEST")).layer] == ["a"]
        st = NetState(phase="TRAIN", stage=["x"])
        assert filter_net(net, st).layer == []

    def test_mixed_legacy_modern_layers_rejected(self):
        with pytest.raises(ValueError, match="legacy"):
            normalize_net(
                NetParameter.from_text(
                    'layers { name: "old" type: RELU } layer { name: "new" type: "ReLU" }'
                )
            )

    def test_v1_blob_multipliers_migrate(self):
        net = normalize_net(
            NetParameter.from_text(
                """
                layers { name: "c" type: CONVOLUTION bottom: "d" top: "c"
                         blobs_lr: 1 blobs_lr: 2 weight_decay: 1 weight_decay: 0 }
                """
            )
        )
        specs = net.layer[0].param
        assert [(s.lr_mult, s.decay_mult) for s in specs] == [(1.0, 1.0), (2.0, 0.0)]

    def test_v1_blob_multipliers_from_reference_file(self):
        import os
        path = "/root/reference/examples/mnist/lenet_consolidated_solver.prototxt"
        if not os.path.exists(path):
            pytest.skip("reference not mounted")
        sp = SolverParameter.from_file(path)
        net = normalize_net(sp.net_param)
        conv1 = [l for l in net.layer if l.name == "conv1"][0]
        assert [s.lr_mult for s in conv1.param] == [1.0, 2.0]

    def test_repeated_message_list_form(self):
        net = NetParameter.from_text(
            'layer { name: "c" type: "Convolution" '
            'param: [{ lr_mult: 1 }, { lr_mult: 2 }] }'
        )
        assert [p.lr_mult for p in net.layer[0].param] == [1.0, 2.0]

    def test_solver_type_conflicts(self):
        with pytest.raises(ValueError, match="both"):
            solver_type(SolverParameter.from_text('type: "Adam" solver_type: SGD'))
        with pytest.raises(ValueError, match="unknown legacy"):
            solver_type(SolverParameter.from_text("solver_type: 9"))

    def test_legacy_upgrade(self):
        net = normalize_net(
            NetParameter.from_text(
                """
                input: "data"
                input_dim: 1 input_dim: 3 input_dim: 4 input_dim: 4
                layers { name: "conv" type: CONVOLUTION bottom: "data" top: "conv" }
                """
            )
        )
        assert net.layer[0].type == "Input"
        assert net.layer[0].input_param.shape[0].dim == [1, 3, 4, 4]
        assert net.layer[1].type == "Convolution"

    V0_NET = """
    name: "v0net"
    input: "data"
    input_dim: 2 input_dim: 3 input_dim: 8 input_dim: 8
    layers {
      layer {
        name: "conv1" type: "conv" num_output: 4 kernelsize: 3 pad: 1
        weight_filler { type: "gaussian" std: 0.1 }
        blobs_lr: 1. blobs_lr: 2. weight_decay: 1. weight_decay: 0.
      }
      bottom: "data" top: "conv1"
    }
    layers { layer { name: "relu1" type: "relu" } bottom: "conv1" top: "conv1" }
    layers {
      layer { name: "pool1" type: "pool" kernelsize: 2 stride: 2 pool: AVE }
      bottom: "conv1" top: "pool1"
    }
    layers { layer { name: "drop" type: "dropout" dropout_ratio: 0.3 }
             bottom: "pool1" top: "pool1" }
    layers {
      layer { name: "ip" type: "innerproduct" num_output: 10
              weight_filler { type: "xavier" } }
      bottom: "pool1" top: "ip"
    }
    layers { layer { name: "loss" type: "softmax_loss" }
             bottom: "ip" bottom: "label" top: "loss" }
    """

    def test_v0_net_migrates(self):
        """V0 'layers { layer { ... } }' nets migrate like the reference's
        UpgradeV0Net (upgrade_proto.cpp, V0LayerParameter
        caffe.proto:1473-1559)."""
        net = normalize_net(NetParameter.from_text(self.V0_NET))
        types = {l.name: l.type for l in net.layer}
        assert types == {"input": "Input", "conv1": "Convolution",
                         "relu1": "ReLU", "pool1": "Pooling",
                         "drop": "Dropout", "ip": "InnerProduct",
                         "loss": "SoftmaxWithLoss"}
        conv = net.layer[1]
        assert conv.convolution_param.kernel_size == [3]
        assert conv.convolution_param.pad == [1]
        assert [(s.lr_mult, s.decay_mult) for s in conv.param] == \
            [(1.0, 1.0), (2.0, 0.0)]
        assert net.layer[3].pooling_param.pool == "AVE"
        assert net.layer[4].dropout_param.dropout_ratio == pytest.approx(0.3)

    def test_v0_net_builds_and_runs(self):
        """A migrated V0 net builds a Net and takes a forward pass —
        migration is load-bearing, not just field shuffling."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from caffe_mpi_tpu.net import Net

        # the label bottom needs a feed: give the V0 net a 2nd input
        text = self.V0_NET.replace('input: "data"',
                                   'input: "data" input: "label"')
        text = text.replace(
            "input_dim: 2 input_dim: 3 input_dim: 8 input_dim: 8",
            "input_dim: 2 input_dim: 3 input_dim: 8 input_dim: 8\n"
            "    input_dim: 2 input_dim: 1 input_dim: 1 input_dim: 1")
        net = Net(NetParameter.from_text(text), phase="TRAIN")
        params, state = net.init(jax.random.PRNGKey(0))
        r = np.random.RandomState(0)
        blobs, _, loss = net.apply(
            params, state,
            {"data": jnp.asarray(r.randn(2, 3, 8, 8).astype(np.float32)),
             "label": jnp.asarray(r.randint(0, 10, (2, 1, 1, 1)))},
            train=True, rng=jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))

    def test_v0_data_layer_fields(self):
        net = normalize_net(NetParameter.from_text("""
            layers {
              layer { name: "d" type: "data" source: "train_db"
                      batchsize: 32 scale: 0.004 meanfile: "m.binaryproto"
                      cropsize: 27 mirror: true rand_skip: 5 }
              top: "data" top: "label"
            }
        """))
        d = net.layer[0]
        assert d.type == "Data"
        assert d.data_param.source == "train_db"
        assert d.data_param.batch_size == 32
        assert d.data_param.rand_skip == 5
        assert d.transform_param.scale == pytest.approx(0.004)
        assert d.transform_param.mean_file == "m.binaryproto"
        assert d.transform_param.crop_size == 27
        assert d.transform_param.mirror is True


class TestUpgradeToolShims:
    """The explicit migration entry points the reference ships as
    standalone binaries (tools/upgrade_net_proto_binary.cpp,
    tools/upgrade_solver_proto_text.cpp). The library migrates on every
    load; these tools exist for offline, file-to-file conversion."""

    def test_upgrade_net_proto_binary(self, tmp_path):
        import numpy as np
        from caffe_mpi_tpu.io import _fields, _tag, _varint, encode_blob, \
            load_caffemodel
        from caffe_mpi_tpu.tools.upgrade_net_proto_binary import main
        w = np.arange(12, dtype=np.float32).reshape(3, 4)
        blob = encode_blob(w)
        # V1 encoding: layers (field 2) { name=4, blobs=6 }
        v1 = (_tag(4, 2) + _varint(len(b"ip")) + b"ip"
              + _tag(6, 2) + _varint(len(blob)) + blob)
        src = tmp_path / "old.caffemodel"
        src.write_bytes(_tag(2, 2) + _varint(len(v1)) + bytes(v1))
        dst = tmp_path / "new.caffemodel"
        assert main([str(src), str(dst)]) == 0
        # output uses only the modern `layer` field (100)
        fields = {f for f, _, _ in _fields(dst.read_bytes())}
        assert 100 in fields and 2 not in fields
        out = load_caffemodel(str(dst))
        np.testing.assert_array_equal(out["ip"][0], w)

    def test_upgrade_solver_proto_text(self, tmp_path):
        from caffe_mpi_tpu.proto import SolverParameter
        from caffe_mpi_tpu.tools.upgrade_solver_proto_text import main
        src = tmp_path / "old_solver.prototxt"
        src.write_text('net: "train.prototxt"\nbase_lr: 0.01\n'
                       "solver_type: NESTEROV\n")
        dst = tmp_path / "new_solver.prototxt"
        assert main([str(src), str(dst)]) == 0
        sp = SolverParameter.from_file(str(dst))
        assert sp.type == "Nesterov"
        assert "solver_type" not in dst.read_text()
