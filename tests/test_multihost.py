"""2-process multi-host DP test (jax.distributed over localhost, CPU).

The reference's multi-node path (MPI_Init + global NCCL communicator,
clusters.cpp:8-45, parallel.cpp:166-169) was only ever exercised by
actually running under mpirun — SURVEY §4 flags the missing fake-cluster
test as the gap this build closes. Here two REAL processes (one simulated
2-device host each) form a jax.distributed cluster on localhost and train
through init_distributed + MeshPlan.shard_feeds's
make_array_from_process_local_data branch (parallel/mesh.py:120-123); the
resulting parameters must match a single-process run on the same global
batches — the multi-host analogue of test_parallel.py's DP invariant.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))

NET = """
name: "mh_mlp"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 16 dim: 8 } shape { dim: 16 } } }
layer { name: "ip1" type: "InnerProduct" bottom: "x" top: "h"
        inner_product_param { num_output: 32 weight_filler { type: "xavier" } } }
layer { name: "r" type: "ReLU" bottom: "h" top: "h" }
layer { name: "ip2" type: "InnerProduct" bottom: "h" top: "y"
        inner_product_param { num_output: 4 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t" top: "l" }
"""
SOLVER_TEXT = ('base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" max_iter: 50 '
               'type: "SGD" random_seed: 7')
N_STEPS = 5
GLOBAL_BATCH = 16


def global_batches(n, seed=3):
    r = np.random.RandomState(seed)
    return [{"x": r.randn(GLOBAL_BATCH, 8).astype(np.float32),
             "t": r.randint(0, 4, GLOBAL_BATCH)} for _ in range(n)]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_cluster(tmp_path, mode):
    port = _free_port()
    out = tmp_path / "proc0_params.npz"
    # children set their own platform pins; don't let the suite's leak in
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_HERE, "multihost_worker.py"),
             f"localhost:{port}", "2", str(i), str(out), mode],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host worker timed out")
        logs.append(stdout)
    if any("Multiprocess computations aren't implemented" in l
           for l in logs):
        # this jaxlib's CPU backend cannot form a cross-process
        # computation at all (jax.distributed connects, but the first
        # collective device_put raises) — the test is unrunnable here,
        # not failing. Real multi-host coverage needs a TPU slice.
        pytest.skip("backend cannot run multiprocess computations "
                    "(CPU); multi-host DP needs real devices")
    for i, (p, l) in enumerate(zip(procs, logs)):
        assert p.returncode == 0, f"proc {i} failed:\n{l[-3000:]}"
    return out


def _single_process_reference():
    import jax.numpy as jnp
    from caffe_mpi_tpu.proto import NetParameter, SolverParameter
    from caffe_mpi_tpu.solver import Solver

    sp = SolverParameter.from_text(SOLVER_TEXT)
    sp.net_param = NetParameter.from_text(NET)
    solver = Solver(sp)
    data = global_batches(N_STEPS)
    solver.step(N_STEPS, lambda it: {
        "x": jnp.asarray(data[it]["x"]), "t": jnp.asarray(data[it]["t"])})
    return solver


@pytest.mark.slow
def test_two_process_zero1_with_collective_snapshot(tmp_path):
    """Multi-host ZeRO-1: slots span both processes; training matches
    single-process; snapshot's history gather runs the collective
    process_allgather path and rank 0's files parse + match."""
    out = _run_cluster(tmp_path, "zero")
    got = np.load(out)
    ref = _single_process_reference()
    np.testing.assert_allclose(got["ip1_w"],
                               np.asarray(ref.params["ip1"]["weight"]),
                               rtol=2e-4, atol=1e-6)
    from caffe_mpi_tpu.io import load_solverstate
    state = str(out) + f".snap_iter_{N_STEPS}.solverstate"
    assert os.path.exists(state)
    it, _learned, history, _cur = load_solverstate(state)
    assert it == N_STEPS
    assert len(history) == 4  # (w,b) x 2 layers, 1 SGD slot each
    # the allgathered ip1 weight history equals the single-process slot
    (ref_hist,) = ref.opt_state["ip1"]["weight"]
    ref_hist = np.asarray(ref_hist)
    np.testing.assert_allclose(history[0].reshape(ref_hist.shape), ref_hist,
                               rtol=2e-4, atol=1e-6)


@pytest.mark.slow
def test_two_process_dp_matches_single_process(tmp_path):
    out = _run_cluster(tmp_path, "dp")
    got = np.load(out)

    # single-process reference on the same global batches, in-suite
    solver = _single_process_reference()
    np.testing.assert_allclose(got["ip1_w"],
                               np.asarray(solver.params["ip1"]["weight"]),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(got["ip2_w"],
                               np.asarray(solver.params["ip2"]["weight"]),
                               rtol=2e-4, atol=1e-6)
