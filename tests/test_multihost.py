"""Multi-host training suite (jax.distributed over localhost, CPU).

The reference's multi-node path (MPI_Init + global NCCL communicator,
clusters.cpp:8-45, parallel.cpp:166-169) was only ever exercised by
actually running under mpirun — SURVEY §4 flags the missing fake-cluster
test as the gap this build closes. Two layers here:

1. (slow) 2-process DP/ZeRO math: REAL processes form a cluster and
   train through MeshPlan.shard_feeds's
   make_array_from_process_local_data branch; parameters must match a
   single-process run on the same global batches. Skips where the CPU
   backend cannot form multiprocess computations.
2. (tier-1, ISSUE 11) the ELASTIC runtime, which needs no multiprocess
   computations: 2-process wiring smokes (cluster formation, mesh
   shape, disjoint per-host Feeder striping, per-host quarantine
   journals merged by rank 0) and the host-kill acceptance — a
   `host_loss`-injected worker kill must end in a journaled exit-87 +
   coordinated supervised `--resume auto` restart whose final weights
   are BIT-IDENTICAL to an uninterrupted 2-process baseline
   (tools/multihost_smoke.py). Single-process tests hold the sharded
   (orbax) verified-snapshot scheme, bounded cluster init, and the
   heartbeat mechanism.
"""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)

NET = """
name: "mh_mlp"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 16 dim: 8 } shape { dim: 16 } } }
layer { name: "ip1" type: "InnerProduct" bottom: "x" top: "h"
        inner_product_param { num_output: 32 weight_filler { type: "xavier" } } }
layer { name: "r" type: "ReLU" bottom: "h" top: "h" }
layer { name: "ip2" type: "InnerProduct" bottom: "h" top: "y"
        inner_product_param { num_output: 4 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t" top: "l" }
"""
SOLVER_TEXT = ('base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" max_iter: 50 '
               'type: "SGD" random_seed: 7')
N_STEPS = 5
GLOBAL_BATCH = 16


def global_batches(n, seed=3):
    r = np.random.RandomState(seed)
    return [{"x": r.randn(GLOBAL_BATCH, 8).astype(np.float32),
             "t": r.randint(0, 4, GLOBAL_BATCH)} for _ in range(n)]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_cluster(tmp_path, mode):
    port = _free_port()
    out = tmp_path / "proc0_params.npz"
    # children set their own platform pins; don't let the suite's leak in
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_HERE, "multihost_worker.py"),
             f"localhost:{port}", "2", str(i), str(out), mode],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host worker timed out")
        logs.append(stdout)
    if any("Multiprocess computations aren't implemented" in l
           for l in logs):
        # this jaxlib's CPU backend cannot form a cross-process
        # computation at all (jax.distributed connects, but the first
        # collective device_put raises) — the test is unrunnable here,
        # not failing. Real multi-host coverage needs a TPU slice.
        pytest.skip("backend cannot run multiprocess computations "
                    "(CPU); multi-host DP needs real devices")
    for i, (p, l) in enumerate(zip(procs, logs)):
        assert p.returncode == 0, f"proc {i} failed:\n{l[-3000:]}"
    return out


def _single_process_reference():
    import jax.numpy as jnp
    from caffe_mpi_tpu.proto import NetParameter, SolverParameter
    from caffe_mpi_tpu.solver import Solver

    sp = SolverParameter.from_text(SOLVER_TEXT)
    sp.net_param = NetParameter.from_text(NET)
    solver = Solver(sp)
    data = global_batches(N_STEPS)
    solver.step(N_STEPS, lambda it: {
        "x": jnp.asarray(data[it]["x"]), "t": jnp.asarray(data[it]["t"])})
    return solver


@pytest.mark.slow
def test_two_process_zero1_with_collective_snapshot(tmp_path):
    """Multi-host ZeRO-1: slots span both processes; training matches
    single-process; snapshot's history gather runs the collective
    process_allgather path and rank 0's files parse + match."""
    out = _run_cluster(tmp_path, "zero")
    got = np.load(out)
    ref = _single_process_reference()
    np.testing.assert_allclose(got["ip1_w"],
                               np.asarray(ref.params["ip1"]["weight"]),
                               rtol=2e-4, atol=1e-6)
    from caffe_mpi_tpu.io import load_solverstate
    state = str(out) + f".snap_iter_{N_STEPS}.solverstate"
    assert os.path.exists(state)
    it, _learned, history, _cur = load_solverstate(state)
    assert it == N_STEPS
    assert len(history) == 4  # (w,b) x 2 layers, 1 SGD slot each
    # the allgathered ip1 weight history equals the single-process slot
    (ref_hist,) = ref.opt_state["ip1"]["weight"]
    ref_hist = np.asarray(ref_hist)
    np.testing.assert_allclose(history[0].reshape(ref_hist.shape), ref_hist,
                               rtol=2e-4, atol=1e-6)


@pytest.mark.slow
def test_two_process_dp_matches_single_process(tmp_path):
    out = _run_cluster(tmp_path, "dp")
    got = np.load(out)

    # single-process reference on the same global batches, in-suite
    solver = _single_process_reference()
    np.testing.assert_allclose(got["ip1_w"],
                               np.asarray(solver.params["ip1"]["weight"]),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(got["ip2_w"],
                               np.asarray(solver.params["ip2"]["weight"]),
                               rtol=2e-4, atol=1e-6)


# ===========================================================================
# ISSUE 11 — elastic multi-host runtime (tier-1: no multiprocess
# computations needed)
# ===========================================================================

def _clean_env(**extra):
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "CAFFE_TPU_FAULTS",
                        "CAFFE_TPU_FAULTS_DIR", "CAFFE_SUPERVISED_CHILD")}
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=_ROOT, **extra)
    return env


class TestClusterWiring:
    """2-process wiring asserts: cluster formation through the hardened
    init, global mesh shape, disjoint per-host record striping over a
    real LMDB, per-host quarantine journals merged by rank 0 — all
    without a cross-process computation (the worker asserts; rank 0
    prints WIRING-OK)."""

    def _write_index_lmdb(self, path, n=16):
        from caffe_mpi_tpu.data.datasets import encode_datum
        from caffe_mpi_tpu.data.lmdb_io import write_lmdb
        write_lmdb(path, ((f"{i:08d}".encode(),
                           encode_datum(np.full((1, 6, 6), i, np.uint8),
                                        int(i % 4)))
                          for i in range(n)))

    def test_two_process_wiring(self, tmp_path):
        self._write_index_lmdb(str(tmp_path / "db"))
        port = _free_port()
        # one corrupt record INSIDE each rank's stripe (B=4, world=2:
        # rank 0 owns flats {0..3, 8..11}, rank 1 {4..7, 12..15})
        corrupt = {0: 1, 1: 5}
        procs, logs = [], []
        for i in range(2):
            env = _clean_env(
                CAFFE_TPU_FAULTS=f"record_corrupt:1:0:{corrupt[i]}",
                WIRING_CORRUPT_INDEX=str(corrupt[i]),
                WIRING_PEER_CORRUPT_INDEX=str(corrupt[1 - i]))
            procs.append(subprocess.Popen(
                [sys.executable,
                 os.path.join(_HERE, "multihost_wiring_worker.py"),
                 f"localhost:{port}", "2", str(i), str(tmp_path)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        for p in procs:
            try:
                out, _ = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("wiring worker timed out")
            logs.append(out)
        for i, (p, l) in enumerate(zip(procs, logs)):
            assert p.returncode == 0, f"proc {i} failed:\n{l[-3000:]}"
        assert "WIRING-OK" in logs[0]


class TestElasticRecovery:
    """The ISSUE 11 acceptance bar: a 2-process CPU cluster survives a
    `host_loss`-injected worker kill — the survivor journals
    `host_lost` and exits 87 within host_deadline, both supervisors
    restart with `--resume auto`, the cluster re-forms, and the
    recovered run's final weights are bit-identical to an uninterrupted
    2-process baseline."""

    def test_host_loss_supervised_recovery(self, tmp_path):
        r = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools",
                                          "multihost_smoke.py"),
             "--json", "--workdir", str(tmp_path)],
            env=_clean_env(), cwd=_ROOT, timeout=560,
            capture_output=True, text=True)
        line = next((l for l in r.stdout.splitlines()
                     if l.startswith('{"multihost_smoke"')), None)
        assert line, f"no smoke report:\n{r.stdout[-2000:]}" \
                     f"\n{r.stderr[-2000:]}"
        rep = json.loads(line)["multihost_smoke"]
        assert r.returncode == 0, rep
        assert rep["baseline_rcs"] == [0, 0], rep
        assert rep["recovery_rcs"] == [0, 0], rep
        assert rep["host_loss_detected"], rep
        assert rep["coordinated_restart"], rep
        assert rep["weights_bitwise_equal"], rep
        # the survivor's journal recorded WHICH peer was lost before
        # the exit (the run journal is later rewritten by the recovered
        # run, so the forensic record is the supervisor failure log +
        # the worker stdout asserted inside the smoke); here assert the
        # on-disk artifacts the operator would read
        flog = tmp_path / "recovery" / "s.failures.log"
        assert flog.exists()
        assert "fault/cluster" in flog.read_text()


class TestShardedSnapshots:
    """Single-process half of the sharded-snapshot contract: per-shard
    crc manifests as the commit record, shard corruption detected and
    fallen back from, GC that sweeps whole .orbax dirs, legacy
    manifest-less dirs still resumable."""

    NET = """
    name: "lsq"
    layer { name: "in" type: "Input" top: "x" top: "t"
            input_param { shape { dim: 4 dim: 3 } shape { dim: 4 dim: 1 } } }
    layer { name: "ip" type: "InnerProduct" bottom: "x" top: "pred"
            inner_product_param { num_output: 1
              weight_filler { type: "gaussian" std: 1 } } }
    layer { name: "loss" type: "EuclideanLoss" bottom: "pred"
            bottom: "t" top: "l" }
    """

    def _solver(self, extra=""):
        from caffe_mpi_tpu.proto import SolverParameter
        from caffe_mpi_tpu.proto.config import NetParameter
        from caffe_mpi_tpu.solver import Solver
        sp = SolverParameter.from_text(
            'base_lr: 0.1 max_iter: 50 lr_policy: "fixed" display: 0 '
            f'random_seed: 3 snapshot_format: ORBAX\n{extra}')
        sp.net_param = NetParameter.from_text(self.NET)
        return Solver(sp)

    @staticmethod
    def _feeds(it):
        import jax.numpy as jnp
        r = np.random.RandomState(it % 16)
        x = r.randn(4, 3).astype(np.float32)
        t = (x @ np.array([[1.0], [-2.0], [0.5]]) + 0.3).astype(np.float32)
        return {"x": jnp.asarray(x), "t": jnp.asarray(t)}

    def test_shard_corruption_detected_and_fallen_back(self, tmp_path):
        """The `snapshot_shard_corrupt` site rots one shard of the
        iter-6 set AFTER its manifest lands; explicit restore must
        reject the set, restore_auto must land on the verified iter-4
        set, and the replay must be bit-exact vs uninterrupted."""
        from caffe_mpi_tpu.utils import resilience
        s = self._solver("snapshot: 2")
        s.sp.snapshot_prefix = str(tmp_path / "s")
        resilience.FAULTS.configure("snapshot_shard_corrupt:1:2")
        try:
            s.step(6, self._feeds)  # snapshots at 2, 4; corrupt fires at 6
        finally:
            resilience.FAULTS.configure("")
        s.close()
        final_w = np.asarray(s.params["ip"]["weight"])
        manifests = resilience.iter_snapshot_manifests(str(tmp_path / "s"))
        assert [it for it, _ in manifests] == [6, 4, 2]
        assert resilience.verify_snapshot(manifests[0][1]) is None  # rot
        assert resilience.verify_snapshot(manifests[1][1]) is not None

        fresh = self._solver()
        fresh.sp.snapshot_prefix = str(tmp_path / "s")
        with pytest.raises(resilience.SnapshotCorruptError):
            fresh.restore(str(tmp_path / "s_iter_6.orbax"))
        state = fresh.restore_auto()
        assert state.endswith("s_iter_4.orbax")
        assert fresh.iter == 4
        fresh.step(2, self._feeds)
        fresh.close()
        assert np.array_equal(np.asarray(fresh.params["ip"]["weight"]),
                              final_w)
        # the run journal's resume pointer names the .orbax set
        run = resilience.read_run_manifest(str(tmp_path / "s"))
        assert run["last_snapshot_state"].endswith(".orbax")

    def test_gc_sweeps_whole_orbax_dirs(self, tmp_path):
        """snapshot_keep GC on sharded sets removes the DIRECTORY (no
        leaked shards, no half-deleted set) and never the newest
        verified one."""
        from caffe_mpi_tpu.utils import resilience
        s = self._solver("snapshot: 2 snapshot_keep: 2")
        s.sp.snapshot_prefix = str(tmp_path / "s")
        s.step(6, self._feeds)
        s.close()
        names = sorted(os.listdir(tmp_path))
        assert "s_iter_2.orbax" not in names                # GC'd whole
        assert "s_iter_2.orbax.manifest.json" not in names  # + manifest
        assert {"s_iter_4.orbax", "s_iter_6.orbax"} <= set(names)
        # corrupt BOTH kept sets: the newest verified (none here) rule
        # falls back to refusing to delete what resume still needs
        for it, m in resilience.iter_snapshot_manifests(str(tmp_path / "s")):
            assert resilience.verify_snapshot(m) is not None

    def test_legacy_manifestless_orbax_resumes(self, tmp_path):
        from caffe_mpi_tpu.utils import resilience
        s = self._solver()
        s.sp.snapshot_prefix = str(tmp_path / "s")
        s.step(3, self._feeds)
        s.snapshot()
        s.close()
        # simulate a pre-ISSUE-11 native snapshot: no manifest sidecar
        os.unlink(tmp_path / "s_iter_3.orbax.manifest.json")
        fresh = self._solver()
        fresh.sp.snapshot_prefix = str(tmp_path / "s")
        state = fresh.restore_auto()
        assert state and state.endswith("s_iter_3.orbax")
        assert fresh.iter == 3
        fresh.close()


class TestClusterInit:
    """Bounded cluster formation: retry/backoff around
    jax.distributed.initialize, `coordinator_down` injection, and the
    CLI's journaled exit-87 conversion."""

    def test_retry_recovers_and_exhaustion_is_bounded(self, monkeypatch):
        import jax
        from caffe_mpi_tpu.parallel import mesh
        from caffe_mpi_tpu.utils import resilience
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.append(kw))
        monkeypatch.setattr(jax.distributed, "shutdown", lambda: None)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        resilience.FAULTS.configure("coordinator_down:2")
        try:
            mesh.init_distributed("localhost:1", 2, 0, attempts=4,
                                  base_delay=0.01)
        finally:
            resilience.FAULTS.configure("")
        assert len(calls) == 1  # two injected outages absorbed
        resilience.FAULTS.configure("coordinator_down:-1")  # sticky
        try:
            with pytest.raises(resilience.ClusterError,
                               match="after 3 attempt"):
                mesh.init_distributed("localhost:1", 2, 0, attempts=3,
                                      base_delay=0.01)
        finally:
            resilience.FAULTS.configure("")

    def test_resolve_cluster_validates(self):
        from caffe_mpi_tpu.parallel import mesh
        from caffe_mpi_tpu.utils import resilience
        world, _, _ = mesh.resolve_cluster(None, host_id=0)
        assert world <= 1  # env-less default: single host

        class SP:
            hosts = 2
            coordinator = ""
        with pytest.raises(resilience.ClusterError, match="coordinator"):
            mesh.resolve_cluster(SP(), host_id=0)
        SP.coordinator = "localhost:1"
        with pytest.raises(resilience.ClusterError, match="host id"):
            mesh.resolve_cluster(SP(), host_id=-1)
        assert mesh.resolve_cluster(SP(), host_id=1) == (
            2, "localhost:1", 1)

    def test_cli_exits_87_with_journal_on_cluster_failure(self, tmp_path):
        """`caffe train -hosts 2` against a coordinator that never
        answers (sticky coordinator_down) must journal
        cluster_init_failed and exit EXIT_CLUSTER — never hang."""
        from caffe_mpi_tpu.utils import resilience
        net = tmp_path / "net.prototxt"
        net.write_text(TestShardedSnapshots.NET)
        solver = tmp_path / "solver.prototxt"
        solver.write_text(f'net: "{net}"\nbase_lr: 0.1 max_iter: 4 '
                          f'lr_policy: "fixed" display: 0\n')
        prefix = str(tmp_path / "run" / "s")
        r = subprocess.run(
            [sys.executable, "-m", "caffe_mpi_tpu.tools.cli", "train",
             "-solver", str(solver), "-synthetic",
             "-snapshot_prefix", prefix, "-hosts", "2",
             "-coordinator", "localhost:1", "-host_id", "0"],
            env=_clean_env(CAFFE_TPU_FAULTS="coordinator_down:-1",
                           CAFFE_TPU_INIT_TIMEOUT="2"),
            cwd=_ROOT, timeout=120, capture_output=True, text=True)
        assert r.returncode == resilience.EXIT_CLUSTER, \
            r.stderr[-2000:]
        run = resilience.read_run_manifest(prefix)
        assert run is not None
        assert run["reason"] == "cluster_init_failed"
        assert run["exit_code"] == resilience.EXIT_CLUSTER


class TestHeartbeat:
    """Mechanism unit: loss detection, startup grace, farewell."""

    def _pair(self, tmp_path, deadline=0.3, **kw):
        from caffe_mpi_tpu.utils.resilience import (DirBeatTransport,
                                                    HostHeartbeat)
        t = DirBeatTransport(str(tmp_path))
        mk = lambda host: HostHeartbeat(t, host, 2, deadline,
                                        interval=0.05, grace=0.5,
                                        hard_exit=False, **kw)
        return mk(0), mk(1)

    def test_silent_peer_trips_within_deadline(self, tmp_path):
        lost = []
        a, b = self._pair(tmp_path)
        a.on_lost = lambda p, e: lost.append((p, e))
        for _ in range(6):
            a.tick()
            b.tick()
            time.sleep(0.05)
        assert a.beats_seen(1) > 0 and a.lost is None
        t0 = time.monotonic()
        while a.lost is None and time.monotonic() - t0 < 3:
            a.tick()  # b stopped beating
            time.sleep(0.03)
        assert a.lost is not None and a.lost[0] == 1
        assert lost and lost[0][0] == 1
        assert a.lost_event.is_set()
        # detection latency is deadline-bounded (plus one tick)
        assert time.monotonic() - t0 < 1.5

    def test_farewell_suppresses_mourning(self, tmp_path):
        a, b = self._pair(tmp_path, deadline=0.2)
        a.tick()
        b.tick()
        time.sleep(0.06)
        a.tick()
        b.farewell()  # clean departure, no more beats
        t0 = time.monotonic()
        while time.monotonic() - t0 < 1.0:
            a.tick()
            time.sleep(0.03)
        assert a.lost is None

    def test_startup_grace_tolerates_slow_peer(self, tmp_path):
        """A peer that has NEVER beaten gets deadline+grace (jit
        compile skew), not bare deadline."""
        a, _ = self._pair(tmp_path, deadline=0.1)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 0.4:  # > deadline, < grace
            a.tick()
            time.sleep(0.03)
        assert a.lost is None

    def test_dir_transport_survives_incarnation_restart(self, tmp_path):
        """A restarted publisher's seq-0 must read as an ADVANCE (not
        staleness), and a bye left by a PREVIOUS incarnation must not
        suppress mourning of the current one — the shared directory
        outlives process incarnations."""
        from caffe_mpi_tpu.utils.resilience import DirBeatTransport
        reader = DirBeatTransport(str(tmp_path))
        old = DirBeatTransport(str(tmp_path))
        for s in range(40):
            old.publish(1, s)
        assert reader.latest_seq(1) == 39
        old.farewell(1)  # stale clean-exit marker
        new = DirBeatTransport(str(tmp_path))  # the restarted worker
        new.publish(1, 0)
        assert reader.latest_seq(1) > 39  # new incarnation advances
        assert not reader.is_bye(1)       # old bye cannot silence it
        new.farewell(1)
        assert reader.is_bye(1)           # its OWN bye still counts


class TestQuarantineMerge:
    def test_merge_dedups_and_sorts(self, tmp_path):
        from caffe_mpi_tpu.utils import resilience
        prefix = str(tmp_path / "s")
        assert resilience.quarantine_journal_path(prefix) \
            == prefix + ".quarantine.json"
        assert resilience.quarantine_journal_path(prefix, 1, 2) \
            == prefix + ".quarantine.r1.json"
        ent = lambda i: {"source": "db", "index": i, "key": "",
                         "substitute": i + 1, "reason": "crc", "time": 0}
        for rank, idxs in ((0, [3, 7]), (1, [7, 12])):
            with open(resilience.quarantine_journal_path(
                    prefix, rank, 2), "w") as f:
                json.dump({"schema": 1,
                           "records": [ent(i) for i in idxs]}, f)
        n = resilience.merge_quarantine_journals(prefix)
        assert n == 3  # 7 deduped
        doc = json.load(open(prefix + ".quarantine.json"))
        assert [e["index"] for e in doc["records"]] == [3, 7, 12]
        assert len(doc["merged_from"]) == 2

    def test_merge_noop_single_host(self, tmp_path):
        from caffe_mpi_tpu.utils import resilience
        assert resilience.merge_quarantine_journals(
            str(tmp_path / "s")) == 0
        assert not os.path.exists(tmp_path / "s.quarantine.json")
