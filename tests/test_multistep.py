"""K-step fused training (solver step_chunk > 1): one jitted lax.scan
program runs K full iterations over a device-resident super-batch, one
host dispatch per chunk instead of per iteration.

The contract under test: `step_chunk=K` is an EXECUTION-SCHEDULE knob,
not a semantics knob — params, optimizer state, per-iteration losses,
LR schedule evaluation, event timing (display/test/snapshot), and
snapshot/resume trajectories must all match the classic K=1 path within
f32 tolerance. Covers the ISSUE-1 acceptance matrix: chunk boundaries
straddling display/test_interval/snapshot, lr_policy step changes
mid-chunk, clip_gradients active, iter_size accumulation, and the
dispatch-count reduction itself.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.proto import SolverParameter
from caffe_mpi_tpu.proto.config import NetParameter
from caffe_mpi_tpu.solver import Solver

LSQ_NET = """
name: "lsq"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 4 dim: 3 } shape { dim: 4 dim: 1 } } }
layer { name: "ip" type: "InnerProduct" bottom: "x" top: "pred"
        inner_product_param { num_output: 1
          weight_filler { type: "gaussian" std: 1 }
          bias_filler { type: "gaussian" std: 1 } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "pred" bottom: "t" top: "l" }
"""

CLS_NET = """
name: "cls"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 8 dim: 6 } shape { dim: 8 } } }
layer { name: "ip" type: "InnerProduct" bottom: "x" top: "y"
        inner_product_param { num_output: 3
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t"
        top: "l" }
"""


def make_solver(extra: str = "", net: str = LSQ_NET) -> Solver:
    sp = SolverParameter.from_text(
        f'base_lr: 0.1 max_iter: 1000 lr_policy: "fixed" display: 0 '
        f'random_seed: 7\n{extra}')
    sp.net_param = NetParameter.from_text(net)
    return Solver(sp)


def lsq_data(rng, n=32):
    out = []
    for _ in range(n):
        x = rng.randn(4, 3).astype(np.float32)
        t = (x @ np.array([[1.0], [-2.0], [0.5]]) + 0.3).astype(np.float32)
        out.append({"x": x, "t": t})
    return out


def window_losses(solver) -> np.ndarray:
    return np.array([float(jnp.asarray(l)) for l in solver._loss_window])


def assert_same_training(a: Solver, b: Solver, rtol=1e-5, atol=1e-6):
    assert a.iter == b.iter
    for ln in a.params:
        for pn in a.params[ln]:
            np.testing.assert_allclose(
                np.asarray(a.params[ln][pn]), np.asarray(b.params[ln][pn]),
                rtol=rtol, atol=atol, err_msg=f"params {ln}/{pn}")
    for ln in a.opt_state:
        for pn in a.opt_state[ln]:
            for si, (sa, sb) in enumerate(zip(a.opt_state[ln][pn],
                                              b.opt_state[ln][pn])):
                np.testing.assert_allclose(
                    np.asarray(sa), np.asarray(sb), rtol=rtol, atol=atol,
                    err_msg=f"opt {ln}/{pn}[{si}]")


class TestEquivalence:
    """step_chunk=K training == step_chunk=1 training, f32 tolerance."""

    def test_sgd_lr_step_mid_chunk(self, rng):
        """lr_policy 'step' drops the rate at iters 6 and 13 — inside a
        K=7 chunk, never on its boundary — so the carried iteration
        counter must drive the schedule correctly from INSIDE the scan."""
        data = lsq_data(rng)
        cfg = ('type: "SGD" momentum: 0.9 lr_policy: "multistep" '
               'gamma: 0.5 stepvalue: 6 stepvalue: 13 average_loss: 20')
        a = make_solver(cfg)
        b = make_solver(cfg + " step_chunk: 7")
        a.step(20, lambda it: data[it % 32])
        b.step(20, lambda it: data[it % 32])
        assert a.dispatch_count == 20
        assert b.dispatch_count == 3  # chunks 7 + 7 + 6
        assert_same_training(a, b)
        # per-iteration losses agree (window sized to hold all 20)
        np.testing.assert_allclose(window_losses(a), window_losses(b),
                                   rtol=1e-5, atol=1e-6)

    def test_clip_gradients_and_iter_size(self, rng):
        """grad-norm clipping is a device scalar inside the scan; the
        iter_size micro-accumulation nests inside each scanned step."""
        data = lsq_data(rng, 64)
        cfg = ('type: "SGD" momentum: 0.9 clip_gradients: 0.05 '
               'iter_size: 2 average_loss: 12')
        a = make_solver(cfg)
        b = make_solver(cfg + " step_chunk: 4")
        a.step(12, lambda it: data[it % 64])
        b.step(12, lambda it: data[it % 64])
        assert b.dispatch_count == 3
        assert_same_training(a, b)
        np.testing.assert_allclose(window_losses(a), window_losses(b),
                                   rtol=1e-5, atol=1e-6)

    def test_adam_long_run(self, rng):
        data = lsq_data(rng)
        cfg = 'type: "Adam" momentum: 0.9 momentum2: 0.999 average_loss: 5'
        a = make_solver(cfg)
        b = make_solver(cfg + " step_chunk: 16")
        a.step(33, lambda it: data[it % 32])
        b.step(33, lambda it: data[it % 32])
        assert b.dispatch_count == 3  # 16 + 16 + 1
        assert_same_training(a, b)

    def test_mesh_data_parallel(self, rng):
        """K-step scan under the SPMD mesh: super-batch sharded over
        'data' at axis 2, params carried replicated through the scan."""
        from caffe_mpi_tpu.parallel import MeshPlan
        net = LSQ_NET.replace("dim: 4 dim: 3", "dim: 8 dim: 3") \
                     .replace("dim: 4 dim: 1", "dim: 8 dim: 1")
        data = []
        for _ in range(32):
            x = rng.randn(8, 3).astype(np.float32)
            t = (x @ np.array([[1.0], [-2.0], [0.5]]) + 0.3).astype(np.float32)
            data.append({"x": x, "t": t})
        cfg = 'type: "SGD" momentum: 0.9'
        sp1 = SolverParameter.from_text(
            f'base_lr: 0.1 max_iter: 1000 lr_policy: "fixed" display: 0 '
            f'random_seed: 7\n{cfg}')
        sp1.net_param = NetParameter.from_text(net)
        a = Solver(sp1)
        sp2 = SolverParameter.from_text(
            f'base_lr: 0.1 max_iter: 1000 lr_policy: "fixed" display: 0 '
            f'random_seed: 7\n{cfg} step_chunk: 5')
        sp2.net_param = NetParameter.from_text(net)
        b = Solver(sp2, mesh=MeshPlan.data_parallel())
        a.step(10, lambda it: data[it % 32])
        b.step(10, lambda it: data[it % 32])
        assert b.dispatch_count == 2
        assert_same_training(a, b, rtol=1e-4, atol=1e-5)


class TestEventBoundaries:
    """Chunks auto-shrink so display/test/snapshot land exactly where
    the K=1 schedule puts them."""

    def test_chunk_at_schedule(self):
        s = make_solver("step_chunk: 64 display: 4 test_interval: 6 "
                        "snapshot: 10")
        # display fires AFTER iters 0,4,8..; test BEFORE iters 6,12..;
        # snapshot after iters 9,19.. (when iter becomes a multiple of 10)
        assert s._chunk_at(0, 100) == 1    # display after iter 0
        assert s._chunk_at(1, 100) == 4    # iters 1..4 (display at 4)
        assert s._chunk_at(5, 100) == 1    # test pass due at iter 6
        assert s._chunk_at(6, 100) == 3    # iters 6..8 (display at 8)
        assert s._chunk_at(9, 100) == 1    # snapshot after iter 9
        assert s._chunk_at(13, 2) == 2     # n caps the chunk
        s2 = make_solver("step_chunk: 64")
        assert s2._chunk_at(0, 1000) == 64
        s3 = make_solver()  # default step_chunk preserves K=1
        assert s3._chunk_at(0, 1000) == 1

    def test_display_parity(self, rng, caplog):
        """Smoothed-loss display lines appear at the same iterations with
        the same values in both modes."""
        import logging
        data = lsq_data(rng)
        cfg = 'type: "SGD" momentum: 0.9 display: 5 average_loss: 3'
        records = {}
        for name, extra in (("k1", ""), ("k8", " step_chunk: 8")):
            s = make_solver(cfg + extra)
            with caplog.at_level(logging.INFO, "caffe_mpi_tpu.solver"):
                caplog.clear()
                s.step(17, lambda it: data[it % 32])
            records[name] = [
                (r.args[0], float(r.args[3]))  # (iteration, smoothed loss)
                for r in caplog.records if "Iteration" in r.msg]
        assert [i for i, _ in records["k1"]] == [0, 5, 10, 15]
        assert [i for i, _ in records["k8"]] == [0, 5, 10, 15]
        for (i1, l1), (i2, l2) in zip(records["k1"], records["k8"]):
            assert l1 == pytest.approx(l2, rel=1e-5), f"iter {i1}"

    def test_test_interval_parity(self, rng):
        """test_all fires at identical iterations; chunk never straddles
        a test boundary."""
        data = lsq_data(rng)
        cfg = ('type: "SGD" momentum: 0.9 test_interval: 6 test_iter: 2 '
               'test_initialization: false')
        for extra, want_dispatch in (("", 20), (" step_chunk: 50", 4)):
            s = make_solver(cfg + extra, net=LSQ_NET)
            fired = []
            # the in-training boundary now dispatches the ASYNC eval
            # entrypoint (ISSUE 2); hook it to observe firing iterations
            orig = s._start_eval
            s._start_eval = lambda fns: fired.append(s.iter) or orig(fns)
            s.step(20, lambda it: data[it % 32],
                   test_feed_fns=[lambda k: data[(7 + k) % 32]])
            assert fired == [6, 12, 18], extra
            assert s.dispatch_count == want_dispatch, extra
            # chunks: 6 (iters 0-5), then 6, 6, 2

    def test_test_interval_without_feeds_does_not_clip(self, rng):
        """A configured-but-unused test_interval (no test feeds passed to
        step()) must not clip fusion — the test pass cannot fire, so the
        chunk schedule ignores it."""
        data = lsq_data(rng)
        s = make_solver('type: "SGD" momentum: 0.9 test_interval: 2 '
                        'step_chunk: 10')
        s.step(20, lambda it: data[it % 32])  # no test_feed_fns
        assert s.dispatch_count == 2

    def test_snapshot_boundary_and_resume(self, rng, tmp_path):
        """Interval snapshots land at identical iterations with
        identical bytes, and a resume from a chunk-boundary state
        continues on the uninterrupted trajectory."""
        data = lsq_data(rng)
        cfg = 'type: "Adam" momentum: 0.9 snapshot: 6 average_loss: 2'
        a = make_solver(cfg)
        a.sp.snapshot_prefix = str(tmp_path / "k1")
        b = make_solver(cfg + " step_chunk: 9")
        b.sp.snapshot_prefix = str(tmp_path / "k9")
        a.step(14, lambda it: data[it % 32])
        a.wait_snapshots()
        b.step(14, lambda it: data[it % 32])
        b.wait_snapshots()
        for it in (6, 12):
            pa = tmp_path / f"k1_iter_{it}.caffemodel"
            pb = tmp_path / f"k9_iter_{it}.caffemodel"
            assert pa.exists() and pb.exists()
            wa = np.frombuffer(pa.read_bytes(), np.uint8)
            wb = np.frombuffer(pb.read_bytes(), np.uint8)
            # identical shapes; values within f32 tolerance — compare the
            # restored weights, not raw bytes (low-order bits may differ
            # between the scanned and unscanned XLA programs)
            assert wa.size == wb.size
        # resume from the K-mode iter-6 snapshot and run to 14 at K=9:
        # trajectory must match the uninterrupted K=1 run
        c = make_solver(cfg + " step_chunk: 9")
        # prefix pinned to tmp: the resumed run crosses the iter-12
        # snapshot boundary, and the default prefix litters the repo root
        c.sp.snapshot_prefix = str(tmp_path / "resume")
        c.restore(str(tmp_path / "k9_iter_6.solverstate"))
        assert c.iter == 6
        c.step(8, lambda it: data[it % 32])
        assert_same_training(a, c)

    def test_solver_step_returns_final_loss(self, rng):
        data = lsq_data(rng)
        a = make_solver('type: "SGD" momentum: 0.9')
        b = make_solver('type: "SGD" momentum: 0.9 step_chunk: 5')
        la = a.step(11, lambda it: data[it % 32])
        lb = b.step(11, lambda it: data[it % 32])
        assert la == pytest.approx(lb, rel=1e-5)


class TestDeviceFeedQueue:
    def test_stack_shape_and_prefetch(self):
        from caffe_mpi_tpu.data.feeder import DeviceFeedQueue
        calls = []

        def feed(it):
            calls.append(it)
            return {"x": np.full((4, 3), it, np.float32),
                    "t": np.full((4,), it, np.int32)}

        q = DeviceFeedQueue(feed, iter_size=2)
        try:
            out = q.get(0, 3, hint=(3, 2))
            assert out["x"].shape == (3, 2, 4, 3)
            assert out["t"].shape == (3, 2, 4)
            # micro-iteration striping: iteration j, micro m -> j*2+m
            got = np.asarray(out["x"])[:, :, 0, 0]
            np.testing.assert_array_equal(got, [[0, 1], [2, 3], [4, 5]])
            assert np.asarray(out["t"]).dtype == np.int32
            # the hint was prefetched on the worker; get() must not
            # rebuild it
            q._pending[(3, 2)].result()
            n_before = len(calls)
            out2 = q.get(3, 2)
            assert len(calls) == n_before  # served from prefetch
            np.testing.assert_array_equal(
                np.asarray(out2["x"])[:, :, 0, 0], [[6, 7], [8, 9]])
        finally:
            q.close()

    def test_device_resident_leaves(self):
        """jnp-array feeds (synthetic benches) stack on device without a
        host round-trip path error."""
        from caffe_mpi_tpu.data.feeder import DeviceFeedQueue
        feeds = {"x": jnp.ones((4, 3))}
        q = DeviceFeedQueue(lambda it: feeds, iter_size=1)
        try:
            out = q.get(5, 2)
            assert out["x"].shape == (2, 1, 4, 3)
            assert isinstance(out["x"], jax.Array)
        finally:
            q.close()

    def test_build_error_propagates(self):
        from caffe_mpi_tpu.data.feeder import DeviceFeedQueue

        def bad(it):
            raise RuntimeError("boom")

        q = DeviceFeedQueue(bad)
        try:
            with pytest.raises(RuntimeError, match="boom"):
                q.get(0, 2)
        finally:
            q.close()


class TestSatellites:
    def test_divide_batch_indivisible_raises(self):
        """gpipe micro-batching of an indivisible global batch must fail
        loudly, naming the effective batch — not silently round up."""
        sp = SolverParameter.from_text(
            'base_lr: 0.1 max_iter: 10 lr_policy: "fixed"')
        sp.net_param = NetParameter.from_text(LSQ_NET)  # batch 4
        with pytest.raises(ValueError, match="effective global batch"):
            Solver(sp, gpipe={"stages": 1, "micro": 3})

    def test_synthetic_feeds_structural_labels(self):
        """Integer feeds chosen by CONSUMER structure (1-D bottom of a
        classification loss), not the literal blob name 'label'."""
        from caffe_mpi_tpu.utils.model_shapes import (input_shapes,
                                                      synthetic_feeds)
        npar = NetParameter.from_text(CLS_NET)  # label top named "t"
        shapes = input_shapes(npar, batch=8)
        feeds = synthetic_feeds(shapes, n_classes=3, npar=npar)
        assert jnp.issubdtype(feeds["t"].dtype, jnp.integer)
        assert feeds["t"].shape == (8,)
        assert int(feeds["t"].max()) < 3
        assert jnp.issubdtype(feeds["x"].dtype, jnp.floating)
        # without a net to inspect, 1-D tops still get integer feeds
        feeds2 = synthetic_feeds(shapes, n_classes=3)
        assert jnp.issubdtype(feeds2["t"].dtype, jnp.integer)

    def test_gpipe_clip_no_host_sync(self, rng):
        """gpipe clip_gradients computes the clip scale as a device
        scalar (ADVICE r5) and still matches the sequential trajectory."""
        net = LSQ_NET.replace("dim: 4 dim: 3", "dim: 8 dim: 3") \
                     .replace("dim: 4 dim: 1", "dim: 8 dim: 1")
        data = []
        for _ in range(16):
            x = rng.randn(8, 3).astype(np.float32)
            t = (x @ np.array([[1.0], [-2.0], [0.5]]) + 0.3).astype(np.float32)
            data.append({"x": x, "t": t})
        halves = [{k: v[4 * m:4 * (m + 1)] for k, v in d.items()}
                  for d in data for m in range(2)]
        cfg = 'type: "SGD" momentum: 0.9 clip_gradients: 0.05'
        seq = make_solver(cfg, net=net)
        seq.step(6, lambda it: data[it])
        sp = SolverParameter.from_text(
            f'base_lr: 0.1 max_iter: 1000 lr_policy: "fixed" display: 0 '
            f'random_seed: 7\n{cfg}')
        sp.net_param = NetParameter.from_text(net)
        gp = Solver(sp, gpipe={"stages": 2, "micro": 2})
        gp.step(6, lambda it: halves[it])
        assert_same_training(seq, gp, rtol=1e-4, atol=1e-5)
