"""Fleet router contract (ISSUE 18, docs/serving.md "Fleet").

The router half of serving/fleet.py is pure HTTP plumbing — no engine,
no jax, no subprocesses — so its contract is held here with fake
replica clients: least-loaded spread, typed-retry policy (429/503
retried on a sibling within `serve_retry_budget`, 504/400 NEVER
retried, connection errors typed `replica_lost`), fleet-wide stats/
healthz/readyz aggregation, and the rolling canary swap (a rejection
anywhere leaves the fleet serving the previous weights file — the
same bytes, hence bitwise). The real multi-process replica-kill proof
lives in tools/fleet_smoke.py; the heartbeat revive contract the
supervisor depends on is held at the bottom.
"""

import os
import time

import pytest

from caffe_mpi_tpu.serving.errors import SwapError
from caffe_mpi_tpu.serving.fleet import (FleetRouter, ReplicaHandle,
                                         RETRYABLE_KINDS)
from caffe_mpi_tpu.serving.watch import SnapshotWatcher
from caffe_mpi_tpu.utils import resilience
from caffe_mpi_tpu.utils.resilience import FAULTS

OK = (200, {"predictions": [{"label": 0, "score": 1.0}]})
SHED = (429, {"error": "shed", "kind": "shed"})
UNHEALTHY = (503, {"error": "breaker open", "kind": "unhealthy"})
DEADLINE = (504, {"error": "deadline", "kind": "deadline"})
BAD = (400, {"error": "bad bytes", "kind": "bad_request"})
SWAP_OK = (200, {"swapped": True})
SWAP_REJECT = (500, {"error": "canary scores are non-finite",
                     "kind": "swap"})


class FakeClient:
    """Scripted replica: `responses` is consumed one per classify call
    (the last entry repeats); an Exception entry is raised instead of
    returned (connection-level death). Swap calls are recorded with
    their payloads."""

    def __init__(self, responses=(OK,), swap=(SWAP_OK,), ready=True,
                 stats=None):
        self._responses = list(responses)
        self._swap = list(swap)
        self.ready = ready
        self.stats_doc = stats if stats is not None else {"requests": 0}
        self.classify_calls = 0
        self.swap_calls = []

    def _next(self, script):
        r = script.pop(0) if len(script) > 1 else script[0]
        if isinstance(r, Exception):
            raise r
        return r

    def classify(self, body, content_type=""):
        self.classify_calls += 1
        return self._next(self._responses)

    def get(self, path):
        if path == "/readyz":
            return (200, {"ready": True}) if self.ready \
                else (503, {"ready": False})
        if path == "/stats":
            return 200, self.stats_doc
        return 404, {"kind": "not_found"}

    def swap(self, payload):
        self.swap_calls.append(dict(payload))
        return self._next(self._swap)


def make_router(clients, **kw):
    handles = [ReplicaHandle(i, client=c) for i, c in enumerate(clients)]
    return FleetRouter(handles, **kw)


# ---------------------------------------------------------------------------
# routing + retry policy
# ---------------------------------------------------------------------------

def test_least_loaded_pick():
    router = make_router([FakeClient(), FakeClient(), FakeClient()])
    router.handle(0).in_flight = 2
    router.handle(1).in_flight = 0
    router.handle(2).in_flight = 1
    h = router._pick(set())
    assert h.rid == 1
    assert h.in_flight == 1  # the pick claims a slot


def test_idle_fleet_still_spreads():
    a, b = FakeClient(), FakeClient()
    router = make_router([a, b])
    for _ in range(4):
        status, _ = router.classify(b"img")
        assert status == 200
    # in_flight ties on every request (synchronous calls release the
    # slot); the rotating tiebreak must still alternate replicas
    assert a.classify_calls == 2 and b.classify_calls == 2


def test_shed_retried_on_sibling_and_absorbed():
    # the first request's rotating tiebreak picks rid 1 — make IT shed
    absorber, shedder = FakeClient(), FakeClient(responses=[SHED])
    router = make_router([absorber, shedder], retry_budget=1)
    status, doc = router.classify(b"img")
    assert status == 200
    assert shedder.classify_calls == 1 and absorber.classify_calls == 1
    assert router.retries == 1
    assert router.sheds_absorbed == 1


def test_unhealthy_retried_on_sibling():
    absorber, sick = FakeClient(), FakeClient(responses=[UNHEALTHY])
    router = make_router([absorber, sick], retry_budget=1)
    status, _ = router.classify(b"img")
    assert status == 200
    assert router.retries == 1


def test_retry_budget_exhausted_goes_typed():
    clients = [FakeClient(responses=[SHED]) for _ in range(3)]
    router = make_router(clients, retry_budget=1)
    status, doc = router.classify(b"img")
    assert status == 429 and doc["kind"] == "shed"
    # budget 1 = the original attempt + ONE sibling, not the whole fleet
    assert sum(c.classify_calls for c in clients) == 2
    assert router.retries == 1


@pytest.mark.parametrize("resp", [DEADLINE, BAD])
def test_terminal_kinds_never_retried(resp):
    assert resp[1]["kind"] not in RETRYABLE_KINDS
    sibling = FakeClient()
    failing = FakeClient(responses=[resp])
    router = make_router([sibling, failing], retry_budget=3)
    status, doc = router.classify(b"img")
    assert (status, doc["kind"]) == (resp[0], resp[1]["kind"])
    assert sibling.classify_calls == 0  # the sibling never saw it
    assert router.retries == 0


def test_conn_error_typed_retried_and_drained():
    survivor = FakeClient()
    dead = FakeClient(responses=[ConnectionRefusedError("down")])
    router = make_router([survivor, dead], retry_budget=1)
    status, _ = router.classify(b"img")
    assert status == 200
    assert router.conn_errors == 1
    # the corpse left rotation without waiting for the heartbeat
    assert router.health()["in_rotation"] == 1
    assert not router.handle(1).in_rotation


def test_conn_error_with_no_budget_is_replica_lost():
    dead = FakeClient(responses=[ConnectionRefusedError("down")])
    router = make_router([dead], retry_budget=0)
    status, doc = router.classify(b"img")
    assert status == 503 and doc["kind"] == "replica_lost"


def test_empty_rotation_is_typed_unhealthy():
    router = make_router([FakeClient(), FakeClient()])
    router.mark_down(0)
    router.mark_down(1)
    status, doc = router.classify(b"img")
    assert status == 503 and doc["kind"] == "unhealthy"


# ---------------------------------------------------------------------------
# fleet-wide aggregation
# ---------------------------------------------------------------------------

def test_stats_aggregation():
    a = FakeClient(stats={"requests": 7, "compile_count": 2})
    b = FakeClient(responses=[ConnectionRefusedError("down")],
                   stats={"requests": 3})
    router = make_router([a, b], retry_budget=1)
    router.classify(b"img")
    router.classify(b"img")
    doc = router.stats()
    fleet = doc["fleet"]
    assert fleet["replicas"] == 2 and fleet["routed"] == 2
    assert doc["replicas"]["0"]["requests"] == 7
    assert doc["replicas"]["1"]["requests"] == 3  # stats still reachable


def test_healthz_aggregation():
    router = make_router([FakeClient(), FakeClient()])
    assert router.health()["healthy"]
    router.mark_down(0)
    assert router.health()["healthy"]  # one survivor suffices
    router.mark_down(1)
    h = router.health()
    assert not h["healthy"] and h["in_rotation"] == 0
    router.mark_up(0)
    assert router.health()["healthy"]


def test_readyz_aggregation():
    a, b = FakeClient(), FakeClient(ready=False)
    router = make_router([a, b])
    ok, doc = router.ready()
    assert not ok and doc["replicas"]["1"]["ready"] is False
    b.ready = True
    ok, _ = router.ready()
    assert ok
    router.mark_down(0)  # out of rotation == not ready fleet-wide
    ok, doc = router.ready()
    assert not ok and doc["replicas"]["0"]["in_rotation"] is False


# ---------------------------------------------------------------------------
# rolling canary swap
# ---------------------------------------------------------------------------

def _weights(tmp_path, name, payload):
    p = tmp_path / name
    p.write_bytes(payload)
    return str(p)


def test_rolling_swap_propagates(tmp_path):
    clients = [FakeClient() for _ in range(3)]
    router = make_router(clients, stage_dir=str(tmp_path / "stage"))
    w = _weights(tmp_path, "v2.caffemodel", b"weights-v2-bytes")
    router.swap_weights("default", w, source="iter_10")
    assert router.swaps == 1
    for i, c in enumerate(clients):
        assert len(c.swap_calls) == 1
        # the canary flag lands on exactly ONE replica — the canary
        assert c.swap_calls[0]["canary"] is (i == 0)
        assert c.swap_calls[0]["source"] == "iter_10"
    # every replica read ONE staged immutable copy, bitwise the source
    staged = clients[0].swap_calls[0]["weights"]
    assert all(c.swap_calls[0]["weights"] == staged for c in clients)
    with open(staged, "rb") as f:
        assert f.read() == b"weights-v2-bytes"
    assert router.current_weights == staged


def test_canary_rejection_touches_no_sibling(tmp_path):
    canary = FakeClient(swap=[SWAP_REJECT])
    rest = [FakeClient(), FakeClient()]
    router = make_router([canary] + rest,
                         stage_dir=str(tmp_path / "stage"))
    w = _weights(tmp_path, "bad.caffemodel", b"poison")
    with pytest.raises(SwapError):
        router.swap_weights("default", w, source="iter_20")
    assert router.swaps == 0 and router.swap_rejections == 1
    assert len(canary.swap_calls) == 1
    assert all(not c.swap_calls for c in rest)  # rollout never started


def test_midrollout_rejection_rolls_back_bitwise(tmp_path):
    prev = _weights(tmp_path, "v1.caffemodel", b"previous-bytes")
    ok1, ok2 = FakeClient(), FakeClient()
    rejector = FakeClient(swap=[SWAP_REJECT])
    router = make_router([ok1, rejector, ok2],
                         current_weights=prev,
                         stage_dir=str(tmp_path / "stage"))
    w = _weights(tmp_path, "v2.caffemodel", b"candidate-bytes")
    with pytest.raises(SwapError):
        router.swap_weights("default", w, source="iter_30")
    # the canary had swapped; the rejection must roll it back to the
    # PREVIOUS weights file — the same bytes that were serving before
    assert len(ok1.swap_calls) == 2
    rollback = ok1.swap_calls[1]
    assert rollback["weights"] == prev and rollback["canary"] is False
    with open(rollback["weights"], "rb") as f:
        assert f.read() == b"previous-bytes"
    # the replica AFTER the rejector never saw the candidate at all
    assert not ok2.swap_calls
    assert router.rollbacks == 1 and router.swaps == 0
    assert router.current_weights == prev  # a failed rollout never advances


def test_fleet_swap_canary_bad_site_rots_the_staged_copy(tmp_path):
    clients = [FakeClient()]
    router = make_router(clients, stage_dir=str(tmp_path / "stage"))
    w = _weights(tmp_path, "v3.caffemodel", b"A" * 64)
    FAULTS.configure("fleet_swap_canary_bad:1")
    try:
        router.swap_weights("default", w)
    finally:
        FAULTS.configure("")
    staged = clients[0].swap_calls[0]["weights"]
    with open(staged, "rb") as f:
        rotted = f.read()
    # the site rots the STAGED copy (what the canary replica loads),
    # never the operator's source file
    assert rotted != b"A" * 64
    with open(w, "rb") as f:
        assert f.read() == b"A" * 64


def test_snapshot_watcher_drives_the_router_unmodified(tmp_path):
    """-watch under -replicas: the router IS the watcher's engine —
    same two-method facade, zero watcher changes (the tentpole's
    rolling-swap wiring)."""
    clients = [FakeClient(), FakeClient()]
    router = make_router(clients, stage_dir=str(tmp_path / "stage"))
    prefix = str(tmp_path / "snap")
    mpath = _weights(tmp_path, "snap_iter_10.caffemodel", b"model-bytes")
    spath = _weights(tmp_path, "snap_iter_10.solverstate", b"state")
    resilience.write_snapshot_manifest(spath, 10,
                                       {"model": mpath, "state": spath})
    watcher = SnapshotWatcher(router, "default", prefix, poll_s=0.05)
    assert watcher.check_once()
    assert router.swaps == 1
    assert all(len(c.swap_calls) == 1 for c in clients)
    assert clients[0].swap_calls[0]["source"] == "iter_10"


def test_watcher_rejection_via_router_is_counted(tmp_path):
    clients = [FakeClient(swap=[SWAP_REJECT]), FakeClient()]
    router = make_router(clients, stage_dir=str(tmp_path / "stage"))
    prefix = str(tmp_path / "snap")
    mpath = _weights(tmp_path, "snap_iter_5.caffemodel", b"bad-model")
    spath = _weights(tmp_path, "snap_iter_5.solverstate", b"state")
    resilience.write_snapshot_manifest(spath, 5,
                                       {"model": mpath, "state": spath})
    watcher = SnapshotWatcher(router, "default", prefix, poll_s=0.05)
    assert not watcher.check_once()
    assert router.swap_rejections == 1 and router.swaps == 0
    assert not clients[1].swap_calls
    assert not watcher.check_once()  # rejected iterations stay rejected
    assert len(clients[0].swap_calls) == 1


# ---------------------------------------------------------------------------
# heartbeat revive (the supervisor's respawn re-arm)
# ---------------------------------------------------------------------------

def test_heartbeat_revive_rearms_monitoring(tmp_path):
    hb_dir = str(tmp_path / "hb")
    replica = resilience.DirBeatTransport(hb_dir)
    hb = resilience.HostHeartbeat(
        resilience.DirBeatTransport(hb_dir), host_id=1, n_hosts=2,
        deadline=0.15, grace=0.15, interval=0.05, hard_exit=False)
    replica.publish(0, 0)
    hb.tick()
    assert hb.lost is None and hb.beats_seen(0) >= 1
    # silence past deadline+0 (first contact already made) -> mourned
    time.sleep(0.4)
    hb.tick()
    assert hb.lost is not None and hb.lost[0] == 0
    # ...and tick() latches: nothing is monitored until revive
    hb.revive(0)
    assert hb.lost is None and not hb.lost_event.is_set()
    # a respawned incarnation (new transport instance = new nonce)
    # restarts at seq 0 — the surrogate fold must read it as ADVANCE
    respawned = resilience.DirBeatTransport(hb_dir)
    respawned.publish(0, 0)
    hb.tick()
    assert hb.lost is None
    seen = hb.beats_seen(0)
    respawned.publish(0, 1)
    hb.tick()
    assert hb.beats_seen(0) > seen and hb.lost is None


def test_replica_journal_reasons(tmp_path):
    """replica_dead / fleet_swap journaling through the router's
    journal prefix — the artifact fleet_smoke asserts on."""
    router = make_router([FakeClient()],
                         journal=str(tmp_path / "fleet"),
                         stage_dir=str(tmp_path / "stage"))
    with router._lock:
        router.replica_deaths += 1
    router._journal("replica_dead", replica=0, elapsed_s=1.0)
    doc = resilience.read_run_manifest(str(tmp_path / "fleet") + ".serve")
    assert doc["reason"] == "replica_dead"
    assert doc["replica_deaths"] == 1 and doc["replica"] == 0
    w = _weights(tmp_path, "v9.caffemodel", b"w")
    router.swap_weights("default", w)
    doc = resilience.read_run_manifest(str(tmp_path / "fleet") + ".serve")
    assert doc["reason"] == "fleet_swap" and doc["fleet_swaps"] == 1
    # the cumulative counters survive the overwrite-style journal
    assert doc["replica_deaths"] == 1
