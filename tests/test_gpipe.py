"""Heterogeneous (MPMD GPipe) pipeline parallelism vs sequential.

The bar (VERDICT r3 next-6): a reference-zoo CNN — stages that differ in
computation and activation shape, which the SPMD shift register cannot
express — pipelined across 4 virtual stages with loss/grads/state matching
the sequential microbatch loop. Reference analogue: none (Caffe-MPI's
ForwardFromTo is a single-device sequential loop, net.cpp:669-682).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.net import Net
from caffe_mpi_tpu.parallel.gpipe import GPipe, auto_boundaries, boundary_blobs
from caffe_mpi_tpu.proto import NetParameter

SMALL_CNN = """
name: "gpipe_cnn"
layer { name: "in" type: "Input" top: "data" top: "label"
        input_param { shape { dim: 2 dim: 3 dim: 16 dim: 16 }
                      shape { dim: 2 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param { num_output: 8 kernel_size: 3 pad: 1
          weight_filler { type: "msra" } } }
layer { name: "bn1" type: "BatchNorm" bottom: "conv1" top: "conv1"
        batch_norm_param { scale_bias: true } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
        pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "conv2" type: "Convolution" bottom: "pool1" top: "conv2"
        convolution_param { num_output: 16 kernel_size: 3 pad: 1 stride: 2
          weight_filler { type: "msra" } } }
layer { name: "relu2" type: "ReLU" bottom: "conv2" top: "conv2" }
layer { name: "ip" type: "InnerProduct" bottom: "conv2" top: "logits"
        inner_product_param { num_output: 10
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
        bottom: "label" top: "loss" }
layer { name: "acc" type: "Accuracy" bottom: "logits" bottom: "label"
        top: "acc" }
"""


def _sequential_reference(net, params, state, feeds_list):
    """The ground truth: microbatches through net.apply in order, loss and
    param-grads averaged (iter_size semantics), state threaded through."""
    def loss_fn(p, s, f):
        _, new_s, loss = net.apply(p, s, f, train=True)
        return loss, new_s

    grads_sum = None
    loss_sum = 0.0
    for feeds in feeds_list:
        (loss, state), g = jax.value_and_grad(loss_fn, has_aux=True)(
            params, state, feeds)
        loss_sum = loss_sum + loss
        grads_sum = g if grads_sum is None else jax.tree.map(
            jnp.add, grads_sum, g)
    inv = 1.0 / len(feeds_list)
    return (loss_sum * inv,
            jax.tree.map(lambda x: x * inv, grads_sum), state)


def _assert_tree_close(a, b, rtol, atol):
    ka, kb = set(a), set(b)
    assert ka == kb, f"tree keys differ: {ka ^ kb}"
    for k in a:
        for p in a[k]:
            np.testing.assert_allclose(
                np.asarray(a[k][p]), np.asarray(b[k][p]),
                rtol=rtol, atol=atol, err_msg=f"{k}/{p}")


def _microbatches(net, n_micro, seed=0):
    r = np.random.RandomState(seed)
    batch = net.blob_shapes["data"][0]
    shape = net.blob_shapes["data"]
    return [{"data": jnp.asarray(r.randn(*shape).astype(np.float32)),
             "label": jnp.asarray(r.randint(0, 10, batch))}
            for _ in range(n_micro)]


class TestSmallCNN:
    def _build(self):
        net = Net(NetParameter.from_text(SMALL_CNN), phase="TRAIN")
        params, state = net.init(jax.random.PRNGKey(0))
        return net, params, state

    def test_boundary_blobs(self):
        net, _, _ = self._build()
        # cut after pool1 (layers 0-4 | 5-): only pool1 + label cross
        names = [l.name for l in net.layers]
        cut = names.index("conv2")
        assert boundary_blobs(net, cut, len(net.layers)) == ["label", "pool1"]

    def test_auto_boundaries_cover_and_start_after_input(self):
        net, _, _ = self._build()
        b = auto_boundaries(net, 3)
        assert b[0] == 0 and b[-1] == len(net.layers) and len(b) == 4
        assert b[1] >= 1  # input layer stays in stage 0

    @pytest.mark.parametrize("n_stages", [2, 3])
    def test_exact_match_vs_sequential(self, n_stages):
        net, params, state = self._build()
        feeds = _microbatches(net, n_micro=4)
        ref_loss, ref_grads, ref_state = _sequential_reference(
            net, params, state, feeds)
        pipe = GPipe(net, n_stages)
        loss, grads, new_state = pipe.train_step(
            pipe.place_params(params), state, feeds)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=1e-5, atol=1e-6)
        _assert_tree_close(grads, ref_grads, rtol=1e-4, atol=1e-6)
        _assert_tree_close(new_state, ref_state, rtol=1e-5, atol=1e-6)

    def test_params_partitioned_across_devices(self):
        net, params, state = self._build()
        pipe = GPipe(net, 3)
        placed = pipe.place_params(params)
        devs = {next(iter(tree.values())).devices().pop()
                for tree in placed.values()}
        assert len(devs) >= 2, "stage params should live on distinct devices"


SHARED_NET = """
name: "gpipe_shared"
layer { name: "in" type: "Input" top: "x" top: "label"
        input_param { shape { dim: 2 dim: 12 } shape { dim: 2 } } }
layer { name: "fc1" type: "InnerProduct" bottom: "x" top: "h1"
        param { name: "w_tied" } param { name: "b_tied" }
        inner_product_param { num_output: 12
          weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "h1" top: "h1" }
layer { name: "fc2" type: "InnerProduct" bottom: "h1" top: "h2"
        param { name: "w_tied" } param { name: "b_tied" }
        inner_product_param { num_output: 12
          weight_filler { type: "xavier" } } }
layer { name: "relu2" type: "ReLU" bottom: "h2" top: "h2" }
layer { name: "out" type: "InnerProduct" bottom: "h2" top: "logits"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
        bottom: "label" top: "loss" }
"""


def test_shared_params_across_stages():
    """A weight-tied net (fc1/fc2 share blobs via ParamSpec.name) split so
    the owner (fc1) and a referencing layer (fc2) land in DIFFERENT
    stages: the referencing stage gets a local replica and the owner's
    grads accumulate contributions from both stages' devices."""
    net = Net(NetParameter.from_text(SHARED_NET), phase="TRAIN")
    assert ("fc2", "weight") in net.param_aliases
    params, state = net.init(jax.random.PRNGKey(2))
    r = np.random.RandomState(5)
    feeds = [{"x": jnp.asarray(r.randn(2, 12).astype(np.float32)),
              "label": jnp.asarray(r.randint(0, 4, 2))} for _ in range(3)]
    ref_loss, ref_grads, _ = _sequential_reference(net, params, state, feeds)

    names = [l.name for l in net.layers]
    cut = names.index("fc2")  # fc1 in stage 0, fc2 in stage 1
    pipe = GPipe(net, boundaries=[0, cut, len(net.layers)])
    assert "fc1" in pipe.param_layers[1], "stage 1 must pull the owner tree"
    loss, grads, _ = pipe.train_step(pipe.place_params(params), state, feeds)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    _assert_tree_close(grads, ref_grads, rtol=1e-4, atol=1e-6)


def test_resnet50_four_stage_pipeline_matches_sequential():
    """The VERDICT bar: ResNet-50 (real zoo topology — heterogeneous
    stages, shapes changing at every stage seam) across 4 virtual
    devices. Input shrunk to 2x3x48x48 (global AVE pool makes the net
    size-agnostic) to keep the CPU run in-suite.

    BN runs on global stats (the finetune configuration): with fresh
    random weights and batch statistics over 8 values, ResNet-50's
    gradient is numerically chaotic — even jit vs eager of the IDENTICAL
    sequential function disagrees by ~20-40% in res5 (measured; rounding
    amplified through 53 BN rsqrt's). Pinning the stats isolates what
    this test is about: the pipeline decomposition, not f32 chaos."""
    import os
    path = os.path.join(os.path.dirname(__file__), "..",
                        "models/resnet50/train_val.prototxt")
    with open(path) as f:
        text = f.read()  # presence is text-level (proto2 has()): patch text
    text = text.replace("batch_norm_param {",
                        "batch_norm_param { use_global_stats: true")
    np_param = NetParameter.from_text(text)
    for lp in np_param.layer:
        if lp.type == "Input":
            lp.input_param.shape[0].dim = [2, 3, 48, 48]
            lp.input_param.shape[1].dim = [2]
    net = Net(np_param, phase="TRAIN")
    params, state = net.init(jax.random.PRNGKey(1))
    feeds = _microbatches(net, n_micro=4, seed=3)

    ref_loss, ref_grads, ref_state = _sequential_reference(
        net, params, state, feeds)
    pipe = GPipe(net, 4)
    # each stage seam must be a narrow cut: one activation + the label
    for s in range(1, 4):
        wire = [b for b in pipe.in_blobs[s] if b != "label"]
        assert len(wire) == 1, f"stage {s} wire {pipe.in_blobs[s]}"
    loss, grads, new_state = pipe.train_step(
        pipe.place_params(params), state, feeds)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    # stage-local jits fuse differently than the eager whole-net
    # reference; grads of O(1e-4) elements see ~2% reduction-order noise
    _assert_tree_close(grads, ref_grads, rtol=1e-3, atol=3e-4)
    _assert_tree_close(new_state, ref_state, rtol=1e-4, atol=1e-5)
