"""Net graph-runtime tests (mirrors reference test_net.cpp scope):
construction from prototxt, forward, loss weighting, in-place ops,
param sharing, frozen params, jax.grad through the whole graph."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.net import Net
from caffe_mpi_tpu.proto import NetParameter

MLP = """
name: "mlp"
layer {
  name: "data" type: "Input" top: "data" top: "label"
  input_param { shape { dim: 8 dim: 10 } shape { dim: 8 } }
}
layer {
  name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
  inner_product_param { num_output: 16 weight_filler { type: "xavier" } }
}
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer {
  name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
  inner_product_param { num_output: 4 weight_filler { type: "xavier" } }
}
layer {
  name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" bottom: "label" top: "loss"
  include { phase: TRAIN }
}
layer {
  name: "acc" type: "Accuracy" bottom: "ip2" bottom: "label" top: "acc"
  include { phase: TEST }
}
"""


def feeds(rng):
    return {
        "data": jnp.asarray(rng.randn(8, 10).astype(np.float32)),
        "label": jnp.asarray(rng.randint(0, 4, 8)),
    }


class TestNetBuild:
    def test_shapes_and_phase(self):
        net = Net(NetParameter.from_text(MLP), phase="TRAIN")
        assert [l.name for l in net.layers] == ["data", "ip1", "relu1", "ip2", "loss"]
        assert net.blob_shapes["ip1"] == (8, 16)
        assert net.blob_shapes["ip2"] == (8, 4)
        assert net.blob_shapes["loss"] == ()
        assert net.loss_blobs == [("loss", 1.0)]
        test_net = Net(NetParameter.from_text(MLP), phase="TEST")
        assert [l.name for l in test_net.layers][-1] == "acc"
        assert test_net.loss_blobs == []

    def test_unknown_bottom_raises(self):
        bad = 'layer { name: "r" type: "ReLU" bottom: "nope" top: "y" }'
        with pytest.raises(ValueError, match="unknown bottom"):
            Net(NetParameter.from_text(bad))

    def test_forward_and_loss(self, rng):
        net = Net(NetParameter.from_text(MLP), phase="TRAIN")
        params, state = net.init(jax.random.PRNGKey(0))
        blobs, _, loss = net.apply(params, state, feeds(rng), train=True,
                                   rng=jax.random.PRNGKey(1))
        assert blobs["loss"].shape == ()
        assert float(loss) == pytest.approx(float(blobs["loss"]))
        assert float(loss) > 0

    def test_grad_through_net(self, rng):
        net = Net(NetParameter.from_text(MLP), phase="TRAIN")
        params, state = net.init(jax.random.PRNGKey(0))
        fd = feeds(rng)

        def loss_fn(p):
            _, _, loss = net.apply(p, state, fd, train=True,
                                   rng=jax.random.PRNGKey(1))
            return loss

        grads = jax.grad(loss_fn)(params)
        assert set(grads) == {"ip1", "ip2"}
        assert float(jnp.sum(jnp.abs(grads["ip1"]["weight"]))) > 0

    def test_frozen_param_gets_zero_grad(self, rng):
        frozen = MLP.replace(
            'name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"',
            'name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"\n'
            '  param { lr_mult: 0 } param { lr_mult: 0 }',
        )
        net = Net(NetParameter.from_text(frozen), phase="TRAIN")
        params, state = net.init(jax.random.PRNGKey(0))
        fd = feeds(rng)
        grads = jax.grad(
            lambda p: net.apply(p, state, fd, train=True,
                                rng=jax.random.PRNGKey(1))[2]
        )(params)
        assert float(jnp.sum(jnp.abs(grads["ip1"]["weight"]))) == 0.0
        assert float(jnp.sum(jnp.abs(grads["ip2"]["weight"]))) > 0

    def test_param_sharing(self, rng):
        shared = """
        layer { name: "in" type: "Input" top: "x"
                input_param { shape { dim: 2 dim: 5 } } }
        layer { name: "a" type: "InnerProduct" bottom: "x" top: "a"
                param { name: "w" } param { name: "bb" }
                inner_product_param { num_output: 5 weight_filler { type: "xavier" } } }
        layer { name: "b" type: "InnerProduct" bottom: "a" top: "b"
                param { name: "w" } param { name: "bb" }
                inner_product_param { num_output: 5 weight_filler { type: "xavier" } } }
        """
        net = Net(NetParameter.from_text(shared))
        params, state = net.init(jax.random.PRNGKey(0))
        assert "b" not in params  # layer b aliases layer a's params
        assert net.param_aliases[("b", "weight")] == ("a", "weight")
        x = jnp.asarray(rng.randn(2, 5).astype(np.float32))
        blobs, _, _ = net.apply(params, state, {"x": x}, train=False)
        w, bias = np.array(params["a"]["weight"]), np.array(params["a"]["bias"])
        expect = (np.array(x) @ w.T + bias) @ w.T + bias
        np.testing.assert_allclose(np.array(blobs["b"]), expect, rtol=1e-4)

    def test_in_place_and_loss_weight(self, rng):
        two_loss = MLP.replace(
            'name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" bottom: "label" top: "loss"',
            'name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" bottom: "label" top: "loss"\n'
            '  loss_weight: 2.5',
        )
        net = Net(NetParameter.from_text(two_loss), phase="TRAIN")
        params, state = net.init(jax.random.PRNGKey(0))
        blobs, _, loss = net.apply(params, state, feeds(rng), train=True,
                                   rng=jax.random.PRNGKey(1))
        assert float(loss) == pytest.approx(2.5 * float(blobs["loss"]), rel=1e-5)

    def test_jit_apply(self, rng):
        net = Net(NetParameter.from_text(MLP), phase="TRAIN")
        params, state = net.init(jax.random.PRNGKey(0))
        fd = feeds(rng)

        @jax.jit
        def step(p, s, f):
            return net.apply(p, s, f, train=True, rng=jax.random.PRNGKey(1))[2]

        l1 = step(params, state, fd)
        l2 = step(params, state, fd)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)


class TestReferenceZooDeploy:
    """Build real reference deploy nets end-to-end (shape inference across
    the whole zoo is the strongest graph-construction test)."""

    @pytest.mark.parametrize("path,out_blob,classes", [
        ("/root/reference/models/bvlc_alexnet/deploy.prototxt", "prob", 1000),
        ("/root/reference/models/bvlc_googlenet/deploy.prototxt", "prob", 1000),
        ("/root/reference/models/resnet18/deploy.prototxt", "prob", 1000),
    ])
    def test_deploy_builds(self, path, out_blob, classes):
        import os
        if not os.path.exists(path):
            pytest.skip("reference not mounted")
        net = Net(NetParameter.from_file(path), phase="TEST")
        assert net.blob_shapes[out_blob][1] == classes
