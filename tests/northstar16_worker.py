"""Subprocess target for the 16-virtual-device north-star tests.

Run as: python northstar16_worker.py <mode> <out.npz>
  mode "dp8_tp2"    — data=8 x model=2 mesh, ip1 tensor-parallel
  mode "dp16_zero1" — data=16 mesh with ZeRO-1 optimizer sharding

BASELINE.md's ladder ends at a v5e-16 slice (ResNet-50, 16 chips); the
reference's in-process analogue is its k-device multi-GPU solver test
(reference src/caffe/test/test_gradient_based_solver.cpp:201-217). No
16-chip hardware exists here, so the topology runs on 16 virtual CPU
devices — the same GSPMD partitioning XLA would emit for the real slice.
The parent (test_northstar16.py) compares the final params against a
single-device run on identical global batches: the 16-way shardings must
be value-neutral.
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, os.pardir))
sys.path.insert(0, _HERE)

import jax  # noqa: E402

# the axon sitecustomize pinned jax_platforms at startup; re-pin to CPU
# before any computation (backends init lazily)
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from caffe_mpi_tpu.parallel import MeshPlan  # noqa: E402
from caffe_mpi_tpu.proto import NetParameter, SolverParameter  # noqa: E402
from caffe_mpi_tpu.solver import Solver  # noqa: E402
from test_northstar16 import (  # noqa: E402
    N_STEPS, NET, SOLVER_TEXT, global_batches)


def main():
    mode, out_path = sys.argv[1], sys.argv[2]
    assert len(jax.devices()) == 16, len(jax.devices())

    if mode == "dp8_tp2":
        plan = MeshPlan.from_shape(data=8, model=2)
        sp = SolverParameter.from_text(SOLVER_TEXT)
        shardings = {"ip1": ("model", None)}
    elif mode == "dp16_zero1":
        plan = MeshPlan.from_shape(data=16, model=1)
        sp = SolverParameter.from_text(SOLVER_TEXT + " zero_stage: 1")
        shardings = None
    else:
        raise SystemExit(f"unknown mode {mode!r}")

    sp.net_param = NetParameter.from_text(NET)
    solver = Solver(sp, mesh=plan, param_shardings=shardings)

    if mode == "dp16_zero1":
        # ZeRO-1: the momentum slot really is split 16 ways over 'data'
        (hist,) = solver.opt_state["ip1"]["weight"]
        assert hist.sharding.spec and hist.sharding.spec[0] == "data", \
            hist.sharding.spec
        assert len(hist.sharding.device_set) == 16
    else:
        # TP: ip1's weight is materially sharded over 'model'
        w = solver.params["ip1"]["weight"]
        assert not w.sharding.is_fully_replicated, w.sharding

    data = global_batches(N_STEPS)
    solver.step(N_STEPS, lambda it: {
        "x": jnp.asarray(data[it]["x"]), "t": jnp.asarray(data[it]["t"])})

    np.savez(out_path,
             ip1_w=np.asarray(solver.params["ip1"]["weight"]),
             ip2_w=np.asarray(solver.params["ip2"]["weight"]))
    print(f"northstar16 {mode}: OK")


if __name__ == "__main__":
    main()
