"""Degraded-mode elasticity suite (ISSUE 19).

The reference's multi-node path dies permanently with any rank
(clusters.cpp:8-45 — one MPI_Abort kills the job); PR 10's elastic
restart-all survives TRANSIENT losses but blocks forever re-forming
the original world when a host is gone for good. This suite holds the
generation protocol that reshapes the cluster around the survivors:

1. the generation store (`<prefix>.cluster/`): implicit generation 1,
   atomic publish + history audit trail, torn-record fallback, done
   markers
2. supervisor-beat membership: prime-then-count liveness (a frozen
   beat file never reads as alive), rejoin-wait parking
3. the solver's snapshot-boundary rejoin trigger
   (`_maybe_admit_rejoin`): min_hosts-gated, primes on first boundary,
   raises a journaled `cluster_rejoin` ClusterError on a revival
4. fast-fail doomed formation: consecutive fresh `cluster_init_failed`
   journals stop the restart loop early; a cluster that formed once
   never fast-fails
5. stable quarantine identity: `.h<host>` journals keyed on the
   ORIGINAL host id survive rank remaps; rank 0's merge reads both
   stems
6. cross-world-count snapshot restore: an ORBAX set saved on a 4-way
   mesh restores onto 2-way and back (the degraded resume path)
7. the e2e acceptance: tools/multihost_smoke.py --degrade (permanent
   host-1 loss -> generation 2 at world 1 -> rejoin-wait -> snapshot-
   boundary grow-back to generation 3 -> weights bitwise vs baseline)
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from caffe_mpi_tpu.utils import resilience

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)


# ---------------------------------------------------------------------------
# 1. generation store
# ---------------------------------------------------------------------------

class TestGenerationStore:
    def test_initial_generation_is_implicit(self, tmp_path):
        cdir = resilience.cluster_dir(str(tmp_path / "s"))
        assert cdir == str(tmp_path / "s") + ".cluster"
        assert resilience.read_generation(cdir) is None  # nothing written
        gen = resilience.initial_generation(3, "localhost:9")
        assert gen["generation"] == 1
        assert gen["hosts"] == [0, 1, 2]
        assert gen["world"] == gen["world_full"] == 3
        assert gen["reason"] == "cluster_formed"

    def test_publish_roundtrip_and_history(self, tmp_path):
        cdir = str(tmp_path / "s.cluster")
        gen2 = {"generation": 2, "hosts": [0, 2], "world": 2,
                "world_full": 3, "coordinator": "localhost:7001",
                "reason": "cluster_degraded", "prev_hosts": [0, 1, 2]}
        resilience.write_generation(cdir, gen2)
        got = resilience.read_generation(cdir)
        assert got["generation"] == 2
        assert got["hosts"] == [0, 2]
        assert got["reason"] == "cluster_degraded"
        assert got["time"] > 0
        # the audit trail: per-generation history file
        hist = json.load(open(os.path.join(cdir, "gen_2.json")))
        assert hist["prev_hosts"] == [0, 1, 2]
        # a later generation keeps both history files
        resilience.write_generation(cdir, dict(
            gen2, generation=3, hosts=[0, 1, 2], world=3,
            reason="cluster_regrown"))
        assert os.path.exists(os.path.join(cdir, "gen_2.json"))
        assert resilience.read_generation(cdir)["generation"] == 3

    def test_torn_record_reads_as_none(self, tmp_path):
        cdir = str(tmp_path / "c")
        os.makedirs(cdir)
        with open(resilience.generation_path(cdir), "w") as f:
            f.write('{"generation": 2, "hos')  # torn mid-write
        assert resilience.read_generation(cdir) is None
        with open(resilience.generation_path(cdir), "w") as f:
            json.dump({"generation": 0, "hosts": [0]}, f)  # invalid gen
        assert resilience.read_generation(cdir) is None

    def test_new_generation_clears_stale_done_marker(self, tmp_path):
        """A done marker from an earlier COMPLETED run under this
        prefix must not release the next run's parked rejoiners."""
        cdir = str(tmp_path / "c")
        os.makedirs(cdir)
        with open(os.path.join(cdir, "done"), "w") as f:
            f.write("1\n")
        resilience.write_generation(cdir, {
            "generation": 2, "hosts": [0], "world": 1, "world_full": 2,
            "coordinator": "x:1", "reason": "cluster_degraded"})
        assert not os.path.exists(os.path.join(cdir, "done"))


# ---------------------------------------------------------------------------
# 2. supervisor-beat membership + rejoin-wait
# ---------------------------------------------------------------------------

class TestMembership:
    def test_beating_host_is_live_frozen_host_is_not(self, tmp_path):
        cdir = str(tmp_path / "c")
        # host 1 beats continuously; host 2's file is FROZEN (dead
        # incarnation's last write) — prime-then-count must admit only
        # the beater (plus the observer itself)
        beat = resilience.SupervisorBeat(cdir, 1, 0.05)
        tr = resilience.DirBeatTransport(os.path.join(cdir, "hb"))
        tr.publish(2, 41)  # frozen: never advances again
        beat.start()
        try:
            live = resilience.observe_live_hosts(cdir, 3, 0, 0.6)
        finally:
            beat.stop()
        assert live == [0, 1]

    def test_paused_beat_goes_dark(self, tmp_path):
        cdir = str(tmp_path / "c")
        beat = resilience.SupervisorBeat(cdir, 1, 0.05)
        beat.start()
        try:
            time.sleep(0.2)   # some beats land
            beat.pause()
            time.sleep(0.15)  # in-flight beat drains
            live = resilience.observe_live_hosts(cdir, 2, 0, 0.5)
            assert live == [0]
            beat.resume()
            live = resilience.observe_live_hosts(cdir, 2, 0, 0.5)
            assert live == [0, 1]
        finally:
            beat.stop()

    def test_rejoin_wait_readmission_and_done(self, tmp_path):
        cdir = str(tmp_path / "c")
        os.makedirs(cdir)
        # a generation beyond `beyond` that includes the host releases it
        resilience.write_generation(cdir, {
            "generation": 3, "hosts": [0, 1], "world": 2,
            "world_full": 2, "coordinator": "x:1",
            "reason": "cluster_regrown"})
        got = resilience._rejoin_wait(cdir, 1, 2, park_deadline=5.0)
        assert got["generation"] == 3
        # ...but one that still excludes it parks until the deadline
        assert resilience._rejoin_wait(cdir, 5, 3,
                                       park_deadline=0.6) is None
        # the done marker means the run finished without this host
        with open(os.path.join(cdir, "done"), "w") as f:
            f.write("1\n")
        assert resilience._rejoin_wait(cdir, 5, 3,
                                       park_deadline=5.0) == "done"


class TestClusterGenerationEnv:
    """mesh.cluster_generation parses the env the elastic supervisor
    exports per generation; mesh.publish_generation mirrors it (KV side
    exercised by the smoke — here the parse/no-op halves)."""

    def test_parse_and_absent(self, monkeypatch):
        from caffe_mpi_tpu.parallel import mesh
        for var in ("CAFFE_TPU_CLUSTER_GEN", "CAFFE_TPU_CLUSTER_HOSTS",
                    "CAFFE_TPU_WORLD_FULL", "CAFFE_TPU_CLUSTER_SELF"):
            monkeypatch.delenv(var, raising=False)
        assert mesh.cluster_generation() is None
        assert mesh.publish_generation() is False  # no-op outside a run
        monkeypatch.setenv("CAFFE_TPU_CLUSTER_GEN", "2")
        monkeypatch.setenv("CAFFE_TPU_CLUSTER_HOSTS", "0,2")
        monkeypatch.setenv("CAFFE_TPU_WORLD_FULL", "3")
        monkeypatch.setenv("CAFFE_TPU_CLUSTER_SELF", "2")
        gen = mesh.cluster_generation()
        assert gen == {"generation": 2, "hosts": [0, 2],
                       "world_full": 3, "self": 2}
        monkeypatch.setenv("CAFFE_TPU_CLUSTER_HOSTS", "0,x")
        assert mesh.cluster_generation() is None  # malformed -> None


# ---------------------------------------------------------------------------
# 3. the solver's snapshot-boundary rejoin trigger
# ---------------------------------------------------------------------------

class TestRejoinBoundary:
    NET = """
    name: "lsq"
    layer { name: "in" type: "Input" top: "x" top: "t"
            input_param { shape { dim: 4 dim: 3 } shape { dim: 4 dim: 1 } } }
    layer { name: "ip" type: "InnerProduct" bottom: "x" top: "pred"
            inner_product_param { num_output: 1
              weight_filler { type: "gaussian" std: 1 } } }
    layer { name: "loss" type: "EuclideanLoss" bottom: "pred"
            bottom: "t" top: "l" }
    """

    def _solver(self, min_hosts=1):
        from caffe_mpi_tpu.proto import SolverParameter
        from caffe_mpi_tpu.proto.config import NetParameter
        from caffe_mpi_tpu.solver import Solver
        sp = SolverParameter.from_text(
            'base_lr: 0.1 max_iter: 50 lr_policy: "fixed" display: 0 '
            f'random_seed: 3 min_hosts: {min_hosts}')
        sp.net_param = NetParameter.from_text(self.NET)
        return Solver(sp)

    def test_unset_knob_is_inert(self, tmp_path, monkeypatch):
        monkeypatch.setenv("CAFFE_TPU_CLUSTER_DIR", str(tmp_path / "c"))
        monkeypatch.setenv("CAFFE_TPU_CLUSTER_HOSTS", "0")
        monkeypatch.setenv("CAFFE_TPU_WORLD_FULL", "2")
        s = self._solver(min_hosts=0)
        s._maybe_admit_rejoin()
        assert s._rejoin is None  # never even initialized
        s.close()

    def test_full_generation_disables_check(self, tmp_path, monkeypatch):
        monkeypatch.setenv("CAFFE_TPU_CLUSTER_DIR", str(tmp_path / "c"))
        monkeypatch.setenv("CAFFE_TPU_CLUSTER_HOSTS", "0,1")
        monkeypatch.setenv("CAFFE_TPU_WORLD_FULL", "2")
        s = self._solver()
        s._maybe_admit_rejoin()
        assert s._rejoin is False  # no hosts missing -> permanent no-op
        s.close()

    def test_revival_raises_cluster_rejoin_at_boundary(
            self, tmp_path, monkeypatch):
        """First boundary primes the missing host's (frozen) beat;
        a later boundary that observes an ADVANCE raises the journaled
        grow-back trigger."""
        cdir = str(tmp_path / "c")
        monkeypatch.setenv("CAFFE_TPU_CLUSTER_DIR", cdir)
        monkeypatch.setenv("CAFFE_TPU_CLUSTER_HOSTS", "0")
        monkeypatch.setenv("CAFFE_TPU_WORLD_FULL", "2")
        tr = resilience.DirBeatTransport(os.path.join(cdir, "hb"))
        tr.publish(1, 17)  # the dead incarnation's frozen last beat
        s = self._solver()
        s.sp.snapshot_prefix = str(tmp_path / "s")
        s._maybe_admit_rejoin()            # boundary 1: primes
        assert isinstance(s._rejoin, tuple)
        s._maybe_admit_rejoin()            # frozen file: no advance
        # the host revives: its supervisor's NEW incarnation beats
        revived = resilience.DirBeatTransport(os.path.join(cdir, "hb"))
        revived.publish(1, 0)
        with pytest.raises(resilience.ClusterError) as ei:
            s._maybe_admit_rejoin()
        assert ei.value.journal_reason == "cluster_rejoin"
        assert "snapshot boundary" in str(ei.value)
        run = resilience.read_run_manifest(str(tmp_path / "s"))
        assert run["reason"] == "cluster_rejoin"
        assert run["rejoining_hosts"] == [1]
        assert run["boundary_iter"] == 0
        s.close()


# ---------------------------------------------------------------------------
# 4. fast-fail doomed formation (satellite: crash-loop-of-init guard)
# ---------------------------------------------------------------------------

def _init_fail_child(tmp_path, script):
    """A supervised 'worker' stub: counts its invocations and runs
    `script` (which may journal + exit like cmd_train's cluster exits
    do)."""
    counter = str(tmp_path / "attempts")
    src = (
        "import sys; sys.path.insert(0, %r)\n"
        "from caffe_mpi_tpu.utils import resilience\n"
        "with open(%r, 'a') as f: f.write('x')\n" % (_ROOT, counter)
    ) + script
    return counter, [sys.executable, "-c", src]


class TestFastFailFormation:
    def test_repeated_init_failure_gives_up_early(self, tmp_path):
        """Every attempt journals a fresh cluster_init_failed: the
        supervisor must stop after the SECOND, not burn all 5."""
        prefix = str(tmp_path / "s")
        counter, cmd = _init_fail_child(tmp_path, (
            "resilience.write_run_manifest(%r, "
            "reason='cluster_init_failed', "
            "error='coordinator localhost:1 unreachable', "
            "exit_code=resilience.EXIT_CLUSTER)\n"
            "sys.exit(resilience.EXIT_CLUSTER)\n" % prefix))
        rc = resilience.supervise(
            cmd, cmd, 5, failure_log=prefix + ".failures.log",
            backoff_base=0.05, journal_prefix=prefix)
        assert rc == resilience.EXIT_CLUSTER
        assert len(open(counter).read()) == 2  # initial + ONE retry

    def test_formed_once_never_fast_fails(self, tmp_path):
        """The first attempt fails with a NON-init reason (the cluster
        formed, then lost a host): later init failures must get the
        full restart budget — a restarting peer is exactly what the
        coordinated restart waits for."""
        prefix = str(tmp_path / "s")
        gate = str(tmp_path / "formed_once")
        counter, cmd = _init_fail_child(tmp_path, (
            "import os\n"
            "reason = 'cluster_lost' if not os.path.exists(%r) "
            "else 'cluster_init_failed'\n"
            "open(%r, 'w').close()\n"
            "resilience.write_run_manifest(%r, reason=reason, "
            "error='x', exit_code=resilience.EXIT_CLUSTER)\n"
            "sys.exit(resilience.EXIT_CLUSTER)\n" % (gate, gate, prefix)))
        rc = resilience.supervise(
            cmd, cmd, 3, failure_log=prefix + ".failures.log",
            backoff_base=0.05, journal_prefix=prefix)
        assert rc == resilience.EXIT_CLUSTER
        assert len(open(counter).read()) == 4  # full budget: 1 + 3

    def test_stale_journal_does_not_condemn(self, tmp_path):
        """A cluster_init_failed journal from a PREVIOUS run (stale
        timestamp) must not trip the guard on a child that fails
        without journaling."""
        prefix = str(tmp_path / "s")
        resilience.write_run_manifest(
            prefix, reason="cluster_init_failed", error="old run",
            exit_code=resilience.EXIT_CLUSTER)
        time.sleep(0.05)  # ensure the manifest predates attempt t0
        counter, cmd = _init_fail_child(
            tmp_path, "sys.exit(resilience.EXIT_CLUSTER)\n")
        rc = resilience.supervise(
            cmd, cmd, 2, failure_log=prefix + ".failures.log",
            backoff_base=0.05, journal_prefix=prefix)
        assert rc == resilience.EXIT_CLUSTER
        assert len(open(counter).read()) == 3  # full budget: 1 + 2


# ---------------------------------------------------------------------------
# 5. stable quarantine identity across rank remaps (satellite)
# ---------------------------------------------------------------------------

class TestQuarantineHostIdentity:
    def test_host_keyed_journal_path(self, tmp_path):
        prefix = str(tmp_path / "s")
        # classic spellings unchanged (single-host + rank-keyed)
        assert resilience.quarantine_journal_path(prefix) \
            == prefix + ".quarantine.json"
        assert resilience.quarantine_journal_path(prefix, 1, 2) \
            == prefix + ".quarantine.r1.json"
        # stable host identity wins over the (remappable) rank
        assert resilience.quarantine_journal_path(prefix, 0, 2, host=2) \
            == prefix + ".quarantine.h2.json"
        # single-host runs stay unkeyed even with an identity
        assert resilience.quarantine_journal_path(prefix, 0, 1, host=2) \
            == prefix + ".quarantine.json"

    def test_merge_reads_both_stems(self, tmp_path):
        """A run that degraded mid-way leaves PRE-remap `.r<k>`
        journals and post-remap `.h<host>` journals; rank 0's merge
        must fold both."""
        prefix = str(tmp_path / "s")
        ent = lambda i: {"source": "db", "index": i, "key": "",
                         "substitute": i + 1, "reason": "crc", "time": 0}
        with open(prefix + ".quarantine.r1.json", "w") as f:
            json.dump({"schema": 1, "records": [ent(3), ent(7)]}, f)
        with open(prefix + ".quarantine.h1.json", "w") as f:
            json.dump({"schema": 1, "records": [ent(7), ent(12)]}, f)
        n = resilience.merge_quarantine_journals(prefix)
        assert n == 3  # 7 deduped across the two identities
        doc = json.load(open(prefix + ".quarantine.json"))
        assert [e["index"] for e in doc["records"]] == [3, 7, 12]
        assert len(doc["merged_from"]) == 2


# ---------------------------------------------------------------------------
# 6. cross-world-count snapshot restore (satellite)
# ---------------------------------------------------------------------------

class TestCrossWorldRestore:
    """The degraded resume path: rank 0 restores the last verified
    sharded snapshot onto a SMALLER mesh (W -> W-1) and later back onto
    the full one. restore_native builds its abstract targets from the
    CURRENT topology's shardings, so this works by construction — held
    here against conftest's 8 virtual CPU devices."""

    NET = TestRejoinBoundary.NET

    def _solver(self, n_dev):
        import jax
        from caffe_mpi_tpu.parallel.mesh import MeshPlan
        from caffe_mpi_tpu.proto import SolverParameter
        from caffe_mpi_tpu.proto.config import NetParameter
        from caffe_mpi_tpu.solver import Solver
        sp = SolverParameter.from_text(
            'base_lr: 0.1 max_iter: 50 lr_policy: "fixed" display: 0 '
            'random_seed: 3 snapshot_format: ORBAX')
        sp.net_param = NetParameter.from_text(self.NET)
        mesh = MeshPlan.from_shape(n_dev,
                                   devices=jax.devices()[:n_dev])
        return Solver(sp, mesh=mesh)

    @staticmethod
    def _feeds(it):
        import jax.numpy as jnp
        r = np.random.RandomState(it % 16)
        x = r.randn(4, 3).astype(np.float32)
        t = (x @ np.array([[1.0], [-2.0], [0.5]]) + 0.3).astype(
            np.float32)
        return {"x": jnp.asarray(x), "t": jnp.asarray(t)}

    def test_restore_across_world_sizes(self, tmp_path):
        prefix = str(tmp_path / "s")
        s4 = self._solver(4)
        s4.sp.snapshot_prefix = prefix
        s4.step(3, self._feeds)
        s4.snapshot()
        s4.close()
        w4 = np.asarray(s4.params["ip"]["weight"])
        manifests = resilience.iter_snapshot_manifests(prefix)
        assert manifests and manifests[0][0] == 3
        assert resilience.verify_snapshot(manifests[0][1]) is not None

        # degrade: the same set restores onto HALF the devices
        s2 = self._solver(2)
        s2.sp.snapshot_prefix = prefix
        state = s2.restore_auto()
        assert state and state.endswith("s_iter_3.orbax")
        assert s2.iter == 3
        assert np.array_equal(np.asarray(s2.params["ip"]["weight"]), w4)
        # the degraded generation trains and snapshots on ITS mesh
        s2.step(2, self._feeds)
        s2.snapshot()
        s2.close()
        w2 = np.asarray(s2.params["ip"]["weight"])

        # grow back: the 2-way set restores onto the full mesh
        s4b = self._solver(4)
        s4b.sp.snapshot_prefix = prefix
        state = s4b.restore_auto()
        assert state and state.endswith("s_iter_5.orbax")
        assert s4b.iter == 5
        assert np.array_equal(np.asarray(s4b.params["ip"]["weight"]),
                              w2)
        s4b.close()


# ---------------------------------------------------------------------------
# 7. e2e acceptance: the degrade smoke
# ---------------------------------------------------------------------------

class TestDegradedElasticity:
    def test_permanent_loss_degrade_and_grow_back(self, tmp_path):
        """tools/multihost_smoke.py --degrade: permanent host-1 loss
        (worker AND supervisor dark) -> survivor publishes generation 2
        and continues at world 1 -> revived supervisor parks in
        rejoin-wait -> rank 0 re-admits it at a snapshot boundary ->
        generation 3 (cluster_regrown) at world 2 -> final weights
        bitwise-equal an uninterrupted baseline."""
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        r = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools",
                                          "multihost_smoke.py"),
             "--json", "--degrade", "--workdir", str(tmp_path)],
            env=env, cwd=_ROOT, capture_output=True, text=True,
            timeout=560)
        line = next((l for l in r.stdout.splitlines()
                     if l.startswith('{"multihost_smoke"')), None)
        assert line, (f"no smoke report (rc={r.returncode})\n"
                      f"{r.stdout[-3000:]}\n{r.stderr[-2000:]}")
        rep = json.loads(line)["multihost_smoke"]
        assert r.returncode == 0 and rep["ok"], json.dumps(rep)[:3000]
        assert rep["degraded_generation"]
        assert rep["regrown_generation"]
        assert rep["parked_in_rejoin_wait"]
        assert rep["rejoin_at_snapshot_boundary"]
        assert rep["weights_bitwise_equal"]
