"""16-virtual-device north-star topology tests.

BASELINE.md's capability ladder ends at ResNet-50 on a v5e-16 slice; the
reference's closest test is the in-process multi-GPU solver run
(reference src/caffe/test/test_gradient_based_solver.cpp:201-217 — real
P2PManager over k devices, constant data so k doesn't change results).
This file proves the two 16-way layouts the ladder needs, on 16 virtual
CPU devices (the suite's own process is pinned to 8, so the 16-device
work runs in a worker subprocess):

- data=8 x model=2 (DP x TP): the mesh BASELINE.md names for the
  16-chip rung, with a tensor-parallel dense layer;
- data=16 + ZeRO-1: pure DP at width 16 with optimizer state sharded
  across all devices.

Both must land on the SAME final parameters as a single-device run on
identical global batches — 16-way GSPMD partitioning is value-neutral.
The full-feature dryrun (dp x tp + SP + PP + EP + prototxt surfaces) at
16 devices is covered by test_dryrun_16, which drives the driver's own
__graft_entry__.dryrun_multichip(16) self-spawning path.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.abspath(os.path.join(_HERE, os.pardir))

NET = """
name: "ns16_mlp"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 32 dim: 8 } shape { dim: 32 } } }
layer { name: "ip1" type: "InnerProduct" bottom: "x" top: "h"
        inner_product_param { num_output: 32 weight_filler { type: "xavier" } } }
layer { name: "r" type: "ReLU" bottom: "h" top: "h" }
layer { name: "ip2" type: "InnerProduct" bottom: "h" top: "y"
        inner_product_param { num_output: 4 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t" top: "l" }
"""
SOLVER_TEXT = ('base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" max_iter: 50 '
               'type: "SGD" random_seed: 7')
N_STEPS = 6
GLOBAL_BATCH = 32  # 2 per device at data=16


def global_batches(n, seed=3):
    r = np.random.RandomState(seed)
    return [{"x": r.randn(GLOBAL_BATCH, 8).astype(np.float32),
             "t": r.randint(0, 4, GLOBAL_BATCH)} for _ in range(n)]


def _run_worker(tmp_path, mode):
    out = tmp_path / f"{mode}.npz"
    # the worker sets its own 16-device CPU pin; drop the suite's
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    p = subprocess.run(
        [sys.executable, os.path.join(_HERE, "northstar16_worker.py"),
         mode, str(out)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=600)
    assert p.returncode == 0, f"worker {mode} failed:\n{p.stdout[-3000:]}"
    return np.load(out)


def _single_device_reference():
    import jax.numpy as jnp
    from caffe_mpi_tpu.proto import NetParameter, SolverParameter
    from caffe_mpi_tpu.solver import Solver

    sp = SolverParameter.from_text(SOLVER_TEXT)
    sp.net_param = NetParameter.from_text(NET)
    solver = Solver(sp)
    data = global_batches(N_STEPS)
    solver.step(N_STEPS, lambda it: {
        "x": jnp.asarray(data[it]["x"]), "t": jnp.asarray(data[it]["t"])})
    return solver


@pytest.fixture(scope="module")
def reference_params():
    s = _single_device_reference()
    return {"ip1_w": np.asarray(s.params["ip1"]["weight"]),
            "ip2_w": np.asarray(s.params["ip2"]["weight"])}


@pytest.mark.slow
def test_dp8_tp2_matches_single_device(tmp_path, reference_params):
    got = _run_worker(tmp_path, "dp8_tp2")
    for k in ("ip1_w", "ip2_w"):
        np.testing.assert_allclose(got[k], reference_params[k],
                                   rtol=5e-4, atol=1e-6, err_msg=k)


@pytest.mark.slow
def test_dp16_zero1_matches_single_device(tmp_path, reference_params):
    got = _run_worker(tmp_path, "dp16_zero1")
    for k in ("ip1_w", "ip2_w"):
        np.testing.assert_allclose(got[k], reference_params[k],
                                   rtol=5e-4, atol=1e-6, err_msg=k)


@pytest.mark.slow
def test_weak_scaling_reduction_1_to_8():
    """ISSUE 6 weak-scaling sweep (parallel/reduction.py — reference
    ReduceAndUpdate, net.cpp:757-913): at each data-parallel width the
    bucketed-overlapped step must land on bitwise-identical params vs
    the implicit GSPMD reduction, and every multi-device width must
    emit >= reduce_buckets independent all-reduces per compiled step
    (the collective structure the TPU latency-hiding scheduler overlaps
    with remaining backward; on CPU the count is the tunnel-down
    proxy). n=1 is the fallback baseline: nothing to reduce."""
    sys.path.insert(0, _ROOT)
    import __graft_entry__
    rows = __graft_entry__.weak_scaling_reduction((1, 2, 4, 8))
    assert [r["n_data"] for r in rows] == [1, 2, 4, 8]
    assert all(r["bitwise_vs_implicit"] for r in rows), rows
    for r in rows:
        if r["n_data"] == 1:
            assert r["mode"] == "implicit"
            continue
        assert r["mode"] == "bucketed"
        assert r["hlo_all_reduces"] >= r["collectives_per_step"] >= 3, r
        assert sum(r["bucket_bytes"]) > 0


@pytest.mark.slow
def test_dryrun_16():
    """The driver's own dryrun at 16 devices: dp x tp train step + ZeRO-1,
    ring-attention SP, 16-stage PP, 16-expert EP, prototxt Pipeline + SP
    surfaces — the full MULTICHIP check at the north-star width.
    dryrun_multichip self-spawns a fresh 16-device interpreter when the
    suite's 8-device client can't serve it."""
    sys.path.insert(0, _ROOT)
    import __graft_entry__
    __graft_entry__.dryrun_multichip(16)
