"""Prototxt-level SP and PP surface tests (8-device CPU mesh).

Beyond-reference capabilities (SURVEY §2.7: the reference is DP-only)
made reachable from the model definition: `attention_param {
sequence_parallel: true }` routes to ring attention over the mesh 'model'
axis, and the `Pipeline` layer type runs its repeated block as a GPipe
shift-register over the same axis. The invariant mirrors
test_parallel.py: the distributed execution must produce the SAME
parameter trajectory as plain single-device training.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.parallel import MeshPlan
from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from caffe_mpi_tpu.solver import Solver

SP_NET = """
name: "sp_attn"
layer { name: "in" type: "Input" top: "x" top: "tgt"
        input_param { shape { dim: 8 dim: 10 dim: 16 }
                      shape { dim: 8 dim: 10 dim: 16 } } }
layer { name: "attn" type: "Attention" bottom: "x" top: "a"
        attention_param { num_heads: 4 causal: true sequence_parallel: true
                          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "a" bottom: "tgt" top: "l" }
"""

# block input blob name == the Pipeline layer's bottom ("h")
PP_NET = """
name: "pp_mlp"
layer { name: "in" type: "Input" top: "h" top: "tgt"
        input_param { shape { dim: 8 dim: 16 } shape { dim: 8 dim: 16 } } }
layer { name: "trunk" type: "Pipeline" bottom: "h" top: "y"
        pipeline_param { num_stages: 4 micro_batches: 4
          layer { name: "fc" type: "InnerProduct" bottom: "h" top: "fh"
                  inner_product_param { num_output: 16
                    weight_filler { type: "xavier" } } }
          layer { name: "act" type: "TanH" bottom: "fh" top: "fy" } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "y" bottom: "tgt" top: "l" }
"""

TRANSFORMER_PP_NET = """
name: "tiny_lm_pp"
layer { name: "tok" type: "Input" top: "tokens" top: "label"
        input_param { shape { dim: 4 dim: 12 } shape { dim: 4 dim: 12 } } }
layer { name: "embed" type: "Embed" bottom: "tokens" top: "h"
        embed_param { input_dim: 32 num_output: 24 bias_term: false
                      weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "trunk" type: "Pipeline" bottom: "h" top: "hN"
        pipeline_param { num_stages: 4 micro_batches: 2
          layer { name: "ln1" type: "LayerNorm" bottom: "h" top: "n1" }
          layer { name: "attn" type: "Attention" bottom: "n1" top: "a"
                  attention_param { num_heads: 2 causal: true
                    weight_filler { type: "gaussian" std: 0.1 } } }
          layer { name: "res1" type: "Eltwise" bottom: "h" bottom: "a"
                  top: "r1" }
          layer { name: "ln2" type: "LayerNorm" bottom: "r1" top: "n2" }
          layer { name: "fc1" type: "InnerProduct" bottom: "n2" top: "f1"
                  inner_product_param { num_output: 48 axis: 2
                    weight_filler { type: "gaussian" std: 0.1 } } }
          layer { name: "relu" type: "ReLU" bottom: "f1" top: "f1" }
          layer { name: "fc2" type: "InnerProduct" bottom: "f1" top: "f2"
                  inner_product_param { num_output: 24 axis: 2
                    weight_filler { type: "gaussian" std: 0.1 } } }
          layer { name: "res2" type: "Eltwise" bottom: "r1" bottom: "f2"
                  top: "out" } } }
layer { name: "lnf" type: "LayerNorm" bottom: "hN" top: "hf" }
layer { name: "logits" type: "InnerProduct" bottom: "hf" top: "logits"
        inner_product_param { num_output: 32 axis: 2
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits" bottom: "label"
        top: "loss" softmax_param { axis: 2 } }
"""


def make_solver(net_text, mesh=None, lr=0.05):
    sp = SolverParameter.from_text(
        f'base_lr: {lr} momentum: 0.9 lr_policy: "fixed" max_iter: 50 '
        'type: "SGD" random_seed: 7')
    sp.net_param = NetParameter.from_text(net_text)
    return Solver(sp, mesh=mesh)


def sp_batches(n, seed=3):
    r = np.random.RandomState(seed)
    return [{"x": jnp.asarray(r.randn(8, 10, 16).astype(np.float32)),
             "tgt": jnp.asarray(r.randn(8, 10, 16).astype(np.float32))}
            for _ in range(n)]


def pp_batches(n, seed=4):
    r = np.random.RandomState(seed)
    return [{"h": jnp.asarray(r.randn(8, 16).astype(np.float32)),
             "tgt": jnp.asarray(r.randn(8, 16).astype(np.float32))}
            for _ in range(n)]


def lm_batches(n, seed=5):
    r = np.random.RandomState(seed)
    return [{"tokens": jnp.asarray(r.randint(0, 32, (4, 12))),
             "label": jnp.asarray(r.randint(0, 32, (4, 12)))}
            for _ in range(n)]


class TestSequenceParallelSurface:
    def test_prototxt_flag_parses(self):
        net = NetParameter.from_text(SP_NET)
        assert net.layer[1].attention_param.sequence_parallel is True

    def test_sp_matches_single_device(self):
        """DPxSP (2x4 mesh; seq 10 pads to 12 over the 4-way ring) trains
        to the same parameters as plain single-device attention."""
        data = sp_batches(8)
        s_one = make_solver(SP_NET)
        s_sp = make_solver(SP_NET, mesh=MeshPlan.from_shape(data=2, model=4))
        l1 = s_one.step(5, lambda it: data[it])
        l2 = s_sp.step(5, lambda it: data[it])
        assert l1 == pytest.approx(l2, rel=1e-4)
        np.testing.assert_allclose(
            np.asarray(s_one.params["attn"]["qkv_weight"]),
            np.asarray(s_sp.params["attn"]["qkv_weight"]),
            rtol=2e-4, atol=1e-6)

    def test_flag_without_mesh_is_standard_attention(self):
        s = make_solver(SP_NET)  # no mesh: falls back, must still train
        data = sp_batches(2)
        s.step(2, lambda it: data[it % 2])

    def test_sp_flash_matches_single_device(self):
        """sequence_parallel + use_flash: ring of Pallas flash blocks
        (interpret mode on CPU) from the prototxt surface — same
        trajectory as plain single-device attention."""
        net = SP_NET.replace("sequence_parallel: true",
                             "sequence_parallel: true use_flash: true")
        data = sp_batches(6)
        s_one = make_solver(SP_NET)
        s_sp = make_solver(net, mesh=MeshPlan.from_shape(data=2, model=4))
        l1 = s_one.step(3, lambda it: data[it])
        l2 = s_sp.step(3, lambda it: data[it])
        assert l1 == pytest.approx(l2, rel=1e-4)
        np.testing.assert_allclose(
            np.asarray(s_one.params["attn"]["qkv_weight"]),
            np.asarray(s_sp.params["attn"]["qkv_weight"]),
            rtol=2e-4, atol=1e-6)


class TestPipelineSurface:
    def test_prototxt_parses_and_roundtrips(self):
        net = NetParameter.from_text(PP_NET)
        pp = net.layer[1].pipeline_param
        assert pp.num_stages == 4 and pp.micro_batches == 4
        assert [l.type for l in pp.layer] == ["InnerProduct", "TanH"]
        # text round-trip preserves the nested block
        net2 = NetParameter.from_text(net.to_prototxt())
        assert len(net2.layer[1].pipeline_param.layer) == 2

    def test_stacked_params_and_sequential_semantics(self):
        """Single device: the Pipeline layer is a scan over num_stages
        independent copies of the block — verify against a hand loop."""
        from caffe_mpi_tpu.net import Net
        net = Net(NetParameter.from_text(PP_NET))
        params, state = net.init(jax.random.PRNGKey(0))
        w = params["trunk"]["fc.weight"]
        assert w.shape == (4, 16, 16)
        # stages are independently initialized, not copies
        assert float(jnp.abs(w[0] - w[1]).max()) > 1e-3
        r = np.random.RandomState(0)
        feeds = {"h": jnp.asarray(r.randn(8, 16).astype(np.float32)),
                 "tgt": jnp.zeros((8, 16), jnp.float32)}
        blobs, _, _ = net.apply(params, state, feeds, train=False)
        x = feeds["h"]
        for s in range(4):
            x = jnp.tanh(x @ w[s].T + params["trunk"]["fc.bias"][s])
        np.testing.assert_allclose(np.asarray(blobs["y"]), np.asarray(x),
                                   rtol=1e-5, atol=1e-6)

    def test_pp_matches_single_device(self):
        """DPxPP (2x4 mesh): stage weights sharded one-per-device, batch
        split into microbatches — same trajectory as sequential."""
        data = pp_batches(8)
        s_one = make_solver(PP_NET)
        s_pp = make_solver(PP_NET, mesh=MeshPlan.from_shape(data=2, model=4))
        # stage dim sharded over 'model': the PP memory story
        w = s_pp.params["trunk"]["fc.weight"]
        assert not w.sharding.is_fully_replicated
        l1 = s_one.step(5, lambda it: data[it])
        l2 = s_pp.step(5, lambda it: data[it])
        assert l1 == pytest.approx(l2, rel=1e-4)
        np.testing.assert_allclose(
            np.asarray(s_one.params["trunk"]["fc.weight"]),
            np.asarray(s_pp.params["trunk"]["fc.weight"]),
            rtol=2e-4, atol=1e-6)

    def test_transformer_lm_pp_matches_single_device(self):
        """The VERDICT bar: a transformer LM trains with PP from a
        prototxt, exact-match vs sequential. 4-stage trunk of
        LN->Attention->residual->LN->FFN->residual blocks."""
        data = lm_batches(6)
        s_one = make_solver(TRANSFORMER_PP_NET, lr=0.1)
        s_pp = make_solver(TRANSFORMER_PP_NET, lr=0.1,
                           mesh=MeshPlan.from_shape(data=2, model=4))
        l1 = s_one.step(3, lambda it: data[it])
        l2 = s_pp.step(3, lambda it: data[it])
        assert l1 == pytest.approx(l2, rel=1e-4)
        for pname in ("attn.qkv_weight", "fc1.weight", "ln1.scale"):
            np.testing.assert_allclose(
                np.asarray(s_one.params["trunk"][pname]),
                np.asarray(s_pp.params["trunk"][pname]),
                rtol=5e-4, atol=1e-6, err_msg=pname)

    def test_shape_preserving_enforced(self):
        bad = PP_NET.replace("num_output: 16\n", "num_output: 12\n", 1)
        with pytest.raises(ValueError, match="shape-preserving"):
            make_solver(bad)

    def test_stateful_block_rejected(self):
        bad = PP_NET.replace(
            'layer { name: "act" type: "TanH" bottom: "fh" top: "fy" }',
            'layer { name: "act" type: "BatchNorm" bottom: "fh" top: "fy" }')
        with pytest.raises(ValueError, match="stateful"):
            make_solver(bad)
