"""GPipe wired into Solver + CLI (VERDICT r4 missing #5).

The reference launches its (data) parallelism from the train entrypoint —
tools/caffe.cpp:223-225 hands the solver to P2PManager::Run. The pipelined
analogue here: `caffe train -gpipe S` (or Solver(gpipe=...)) cuts the net
into S device-pinned stages, splits the prototxt batch into micro-batches
(divide_batch semantics, reference parallel.cpp:295-348), runs the MPMD
GPipe wavefront, and applies the optimizer PER STAGE on the stage's own
device over the params it owns. Assertions:

- a trained run matches the sequential Solver parameter-for-parameter on
  the same global batches;
- snapshots written in gpipe mode restore into both gpipe and plain
  solvers (and vice versa) and continue the same trajectory — stage
  placement is a runtime property, not a checkpoint property;
- the test-net evaluation path works with stage-placed params;
- a reference-zoo CNN (GoogLeNet) trains pipelined from one CLI line.
"""

import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from caffe_mpi_tpu.solver import Solver

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.abspath(os.path.join(_HERE, os.pardir))

NET = """
name: "gps_net"
layer { name: "in" type: "Input" top: "data" top: "label"
        input_param { shape { dim: 8 dim: 3 dim: 16 dim: 16 }
                      shape { dim: 8 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "c1"
        convolution_param { num_output: 8 kernel_size: 3 pad: 1
          weight_filler { type: "msra" } } }
layer { name: "r1" type: "ReLU" bottom: "c1" top: "c1" }
layer { name: "pool1" type: "Pooling" bottom: "c1" top: "p1"
        pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "ip1" type: "InnerProduct" bottom: "p1" top: "h"
        inner_product_param { num_output: 32
          weight_filler { type: "xavier" } } }
layer { name: "r2" type: "ReLU" bottom: "h" top: "h" }
layer { name: "ip2" type: "InnerProduct" bottom: "h" top: "y"
        inner_product_param { num_output: 10
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "label"
        top: "l" }
"""
TXT = ('base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" max_iter: 40 '
       'type: "SGD" random_seed: 7')


def make_solver(**kw):
    sp = SolverParameter.from_text(TXT)
    sp.net_param = NetParameter.from_text(NET)
    return Solver(sp, **kw)


def micro_batches(n, seed=3):
    """n half-batches (the gpipe net is built at batch 4 = 8 / micro 2);
    the sequential solver consumes them concatenated in pairs."""
    r = np.random.RandomState(seed)
    return [{"data": jnp.asarray(r.randn(4, 3, 16, 16).astype(np.float32)),
             "label": jnp.asarray(r.randint(0, 10, 4))} for _ in range(n)]


def fulls_from(halves):
    return [{k: jnp.concatenate([halves[2 * i][k], halves[2 * i + 1][k]])
             for k in halves[0]} for i in range(len(halves) // 2)]


def assert_params_close(a, b, rtol=2e-4, atol=1e-6):
    for ln in a.params:
        for pn in a.params[ln]:
            np.testing.assert_allclose(
                np.asarray(a.params[ln][pn]), np.asarray(b.params[ln][pn]),
                rtol=rtol, atol=atol, err_msg=f"{ln}/{pn}")


class TestGPipeSolver:
    def test_divide_batch_and_placement(self):
        s = make_solver(gpipe={"stages": 2, "micro": 2})
        assert s._batch_images() == 4  # prototxt 8 / micro 2
        devs = {next(iter(t.values())).devices().pop()
                for t in s.params.values()}
        assert len(devs) == 2, "params must be partitioned across stages"
        # optimizer slots colocate with their params
        for ln, lo in s.opt_state.items():
            pdev = next(iter(s.params[ln].values())).devices().pop()
            for slots in lo.values():
                for slot in slots:
                    assert slot.devices().pop() == pdev

    def test_trained_run_matches_sequential(self):
        halves = micro_batches(12)
        fulls = fulls_from(halves)
        seq = make_solver()
        seq.step(6, lambda it: fulls[it])
        gp = make_solver(gpipe={"stages": 2, "micro": 2})
        gp.step(6, lambda it: halves[it])
        assert_params_close(seq, gp)

    def test_snapshot_restore_cross_mode(self, tmp_path):
        """gpipe -> plain and plain -> gpipe resume both land on the
        uninterrupted gpipe trajectory (checkpoints are topology-free,
        like the mesh 1<->8 case in test_recipe_fidelity)."""
        halves = micro_batches(16)
        fulls = fulls_from(halves)

        ref = make_solver(gpipe={"stages": 2, "micro": 2})
        ref.step(8, lambda it: halves[it])

        a = make_solver(gpipe={"stages": 2, "micro": 2})
        a.sp.snapshot_prefix = str(tmp_path / "gp")
        a.step(4, lambda it: halves[it])
        path = a.snapshot()

        # resume in gpipe mode
        b = make_solver(gpipe={"stages": 2, "micro": 2})
        b.restore(path)
        assert b.iter == 4
        b.step(4, lambda it: halves[it])
        assert_params_close(ref, b)

        # resume the same snapshot WITHOUT gpipe (sequential full batches)
        c = make_solver()
        c.restore(path)
        c.step(4, lambda it: fulls[it])
        assert_params_close(ref, c, rtol=5e-4)

        # and the reverse: a plain snapshot resumes under gpipe
        d = make_solver()
        d.sp.snapshot_prefix = str(tmp_path / "seq")
        d.step(4, lambda it: fulls[it])
        dpath = d.snapshot()
        e = make_solver(gpipe={"stages": 2, "micro": 2})
        e.restore(dpath)
        e.step(4, lambda it: halves[it])
        assert_params_close(ref, e, rtol=5e-4)

    def test_evaluation_with_stage_placed_params(self):
        sp = SolverParameter.from_text(
            TXT + ' test_iter: 2 test_interval: 0')
        sp.net_param = NetParameter.from_text(NET)  # same net TRAIN+TEST
        s = Solver(sp, gpipe={"stages": 2, "micro": 2})
        halves = micro_batches(4)
        fulls = fulls_from(halves)  # the TEST net keeps the full batch
        s.step(2, lambda it: halves[it])
        scores = s.test_all([lambda k: fulls[k % 2]])
        assert scores and np.isfinite(list(scores[0].values())).all()

    def test_clip_gradients_matches_sequential(self):
        """The clip norm spans all stages (per-stage partial sums, one
        host sync); the clipped trajectory must equal the sequential
        solver's in-jit clip."""
        halves = micro_batches(8)
        fulls = fulls_from(halves)

        def mk(**kw):
            sp = SolverParameter.from_text(TXT + " clip_gradients: 0.8")
            sp.net_param = NetParameter.from_text(NET)
            return Solver(sp, **kw)

        seq = mk()
        seq.step(4, lambda it: fulls[it])
        gp = mk(gpipe={"stages": 2, "micro": 2})
        gp.step(4, lambda it: halves[it])
        assert_params_close(seq, gp, rtol=5e-4)

    def test_global_grad_scale_unwinds(self):
        """fp16 loss scaling under gpipe (reference global_grad_scale):
        the backward seed is scaled, the update unwinds it — in f32 the
        trajectory must match the unscaled run to reassociation
        tolerance (this is what lets the fp16 zoo variants train under
        -gpipe)."""
        halves = micro_batches(8)

        def mk(scale):
            sp = SolverParameter.from_text(
                TXT + (f" global_grad_scale: {scale}" if scale else ""))
            sp.net_param = NetParameter.from_text(NET)
            return Solver(sp, gpipe={"stages": 2, "micro": 2})

        a = mk(0)
        a.step(4, lambda it: halves[it])
        b = mk(1000)
        b.step(4, lambda it: halves[it])
        assert_params_close(a, b, rtol=5e-4, atol=1e-6)

    def test_bf16_storage_trains(self):
        """The fp16 zoo recipe shape (FLOAT16 -> bf16 activations +
        global_grad_scale) trains under gpipe: finite loss, finite f32
        master params."""
        halves = micro_batches(8)
        sp = SolverParameter.from_text(TXT + " global_grad_scale: 1000")
        sp.net_param = NetParameter.from_text(
            'default_forward_type: FLOAT16 default_backward_type: FLOAT16\n'
            + NET)
        s = Solver(sp, gpipe={"stages": 2, "micro": 2})
        loss = s.step(4, lambda it: halves[it])
        assert np.isfinite(loss)
        for ln, lp_ in s.params.items():
            for pn, w in lp_.items():
                assert np.isfinite(np.asarray(w)).all(), f"{ln}/{pn}"

    def test_validation_errors(self):
        from caffe_mpi_tpu.parallel import MeshPlan
        with pytest.raises(ValueError, match="mutually exclusive"):
            make_solver(mesh=MeshPlan.data_parallel(),
                        gpipe={"stages": 2})
        sp = SolverParameter.from_text(TXT + " iter_size: 2")
        sp.net_param = NetParameter.from_text(NET)
        with pytest.raises(ValueError, match="iter_size"):
            Solver(sp, gpipe={"stages": 2})


@pytest.mark.slow
def test_resnet18_training_mode_bn_matches_iter_size(tmp_path):
    """TRAINING-mode BatchNorm through the pipeline at a zoo topology
    (VERDICT r4 weak #7: the training-mode BN pipeline path was only
    covered by a small synthetic net, with the ResNet test pinned to
    use_global_stats).

    Semantics under test: gpipe processes micro-batches sequentially, so
    BN normalizes per micro-batch and running stats thread through in
    order — the SAME contract as the sequential solver's iter_size
    accumulation (and the reference's per-GPU BN under divide_batch:
    each replica normalizes its local batch). So the exact-match
    reference is Solver(iter_size=M) on the identical micro feed
    stream, fresh weights, BN in training mode."""
    from caffe_mpi_tpu.proto import NetParameter, SolverParameter

    npar = NetParameter.from_file(
        os.path.join(_ROOT, "models/resnet18/train_val.prototxt"))
    assert sum(l.type == "BatchNorm" for l in npar.layer) >= 10

    r = np.random.RandomState(2)
    micros = [{"data": jnp.asarray(r.randn(4, 3, 48, 48).astype(np.float32)),
               "label": jnp.asarray(r.randint(0, 1000, 4))}
              for _ in range(6)]

    def mk(iter_size=1, gpipe=None, batch=4):
        # both solvers consume identical batch-4 micro feeds: the gpipe
        # net declares 8 and divide_batch'es to 4 (micro 2); the
        # iter_size reference declares 4 directly
        for l in npar.layer:
            if l.type == "Input" and l.input_param:
                l.input_param.shape[0].dim = [batch, 3, 48, 48]
                l.input_param.shape[1].dim = [batch]
        sp = SolverParameter.from_text(
            'base_lr: 0.01 momentum: 0.9 lr_policy: "fixed" max_iter: 10 '
            f'type: "SGD" random_seed: 9 iter_size: {iter_size}')
        sp.net_param = NetParameter.from_text(npar.to_prototxt())
        return Solver(sp, gpipe=gpipe)

    seq = mk(iter_size=2)
    seq.step(3, lambda it: micros[it])
    gp = mk(gpipe={"stages": 2, "micro": 2}, batch=8)
    gp.step(3, lambda it: micros[it])

    # params AND BN running stats must line up (f32 reassociation only)
    assert_params_close(seq, gp, rtol=1e-3, atol=1e-5)
    for ln, lstate in seq.net_state.items():
        for k, v in lstate.items():
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(gp.net_state[ln][k]),
                rtol=1e-3, atol=1e-5, err_msg=f"state {ln}/{k}")


@pytest.mark.slow
def test_googlenet_trains_pipelined_from_cli(tmp_path):
    """The VERDICT bar: a reference-zoo CNN trains pipelined from ONE CLI
    line. GoogLeNet's own train_val topology (batch shrunk for the CPU
    suite), 4 auto-balanced stages, 2 iterations."""
    npar = NetParameter.from_file(
        os.path.join(_ROOT, "models/googlenet/train_val.prototxt"))
    for l in npar.layer:
        if l.type == "Input" and l.input_param:
            for shape in l.input_param.shape:
                shape.dim[0] = 8
    net_path = tmp_path / "googlenet_small.prototxt"
    net_path.write_text(npar.to_prototxt())
    solver_path = tmp_path / "solver.prototxt"
    solver_path.write_text(
        f'net: "{net_path}"\n'
        'base_lr: 0.01\nmomentum: 0.9\nlr_policy: "fixed"\n'
        'max_iter: 2\ndisplay: 1\n')
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=4")
    p = subprocess.run(
        [sys.executable, "-m", "caffe_mpi_tpu.tools.cli", "train",
         "-solver", str(solver_path), "-synthetic", "-gpipe", "4"],
        env=env, cwd=_ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=1200)
    assert p.returncode == 0, p.stdout[-4000:]
    assert "Optimization done" in p.stdout, p.stdout[-2000:]
