"""Subprocess target for the 2-process multi-host tests (DP and ZeRO-1).

Run as: python multihost_worker.py <coordinator> <num_procs> <proc_id> \
            <out.npz> [dp|zero]

mode "zero" (default "dp") trains with zero_stage: 1 — optimizer slots
sharded across BOTH processes — and takes a snapshot whose history
gather runs the collective process_allgather path.

Each process is one "host" of a jax.distributed cluster on localhost
(CPU backend, 2 local devices each -> 4 global). The process feeds only
its LOCAL slice of the global batch through MeshPlan.shard_feeds, which
on process_count() > 1 assembles the global array from process-local
shards (jax.make_array_from_process_local_data) — the multi-host branch
of parallel/mesh.py:shard_feeds that single-process tests cannot reach.
Process 0 writes the final params for the parent to compare against a
single-process run on the same global batches (test_multihost.py, which
also owns the shared net/batch fixtures).
"""

import os
import sys

# one process = one simulated 2-device host
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, os.pardir))
sys.path.insert(0, _HERE)

import jax  # noqa: E402

# the axon sitecustomize already ran at interpreter startup and PINNED
# jax_platforms via config (env vars set here are too late to win);
# re-pin to CPU the way tests/conftest.py does — backends init lazily,
# so an explicit update before any computation still takes effect
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from caffe_mpi_tpu.parallel import MeshPlan  # noqa: E402
from caffe_mpi_tpu.parallel.mesh import init_distributed  # noqa: E402
from caffe_mpi_tpu.proto import NetParameter, SolverParameter  # noqa: E402
from caffe_mpi_tpu.solver import Solver  # noqa: E402
from test_multihost import (  # noqa: E402
    GLOBAL_BATCH, N_STEPS, NET, SOLVER_TEXT, global_batches)


def main():
    coordinator, num_procs, proc_id, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    mode = sys.argv[5] if len(sys.argv) > 5 else "dp"
    init_distributed(coordinator, num_procs, proc_id)
    assert jax.process_count() == num_procs, jax.process_count()
    assert len(jax.devices()) == 2 * num_procs, len(jax.devices())

    plan = MeshPlan.data_parallel()
    text = SOLVER_TEXT + (" zero_stage: 1" if mode == "zero" else "")
    sp = SolverParameter.from_text(text)
    sp.net_param = NetParameter.from_text(NET)
    solver = Solver(sp, mesh=plan, rank=proc_id)
    if mode == "zero":
        # slots of dim-0-divisible params really live split over 'data'
        # spanning BOTH processes (the multi-host ZeRO case)
        (hist,) = solver.opt_state["ip1"]["weight"]
        assert hist.sharding.spec[0] == "data", hist.sharding.spec
        assert not hist.is_fully_addressable  # remote shards exist

    data = global_batches(N_STEPS)
    local = GLOBAL_BATCH // num_procs

    def feed(it):
        # this process's contiguous slice of the global batch (the
        # Feeder's rank striping, hand-done for the fixture)
        b = data[it]
        sl = slice(proc_id * local, (proc_id + 1) * local)
        return {"x": jnp.asarray(b["x"][sl]), "t": jnp.asarray(b["t"][sl])}

    solver.step(N_STEPS, feed)
    if mode == "zero":
        # snapshot with remote-sharded slots: the history gather is a
        # COLLECTIVE process_allgather, so every rank enters snapshot();
        # async falls back to blocking (collective order must stay
        # rank-identical); only rank 0 writes the two files
        solver.sp.snapshot_prefix = out_path + ".snap"
        solver.snapshot(block=False)
        solver.wait_snapshots()
    if proc_id == 0:
        # params are replicated, so process 0's local replica is the
        # global value
        np.savez(out_path,
                 ip1_w=np.asarray(solver.params["ip1"]["weight"]),
                 ip2_w=np.asarray(solver.params["ip2"]["weight"]))
    jax.distributed.shutdown()
    print(f"proc {proc_id}: OK")


if __name__ == "__main__":
    main()
