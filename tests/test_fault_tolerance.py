"""Survivable training (ISSUE 3, utils/resilience.py): verified atomic
snapshots, dispatch watchdog, supervised auto-resume, fault-injection
plane.

The acceptance bar: an injected feeder error, a corrupted snapshot, a
kill-mid-write, and a simulated dispatch stall must each end in a
successful auto-resume that is ITERATION-EXACT vs an uninterrupted run —
same final weight bits on CPU. The e2e scenarios drive the real CLI in
subprocesses (the kill/stall faults `os._exit`, so in-process is not an
option) over a tiny LMDB-backed net; unit tests cover the mechanism
pieces in-process.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.proto import SolverParameter
from caffe_mpi_tpu.proto.config import NetParameter
from caffe_mpi_tpu.solver import Solver
from caffe_mpi_tpu.utils import resilience
from caffe_mpi_tpu.utils.resilience import (
    DispatchWatchdog, FaultPlane, atomic_output, gc_snapshots,
    iter_snapshot_manifests, retrying, verify_snapshot,
    write_snapshot_manifest)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# unit: atomic publication + manifests + GC
# ---------------------------------------------------------------------------

class TestAtomicManifests:
    def test_atomic_output_publishes_or_nothing(self, tmp_path):
        path = str(tmp_path / "f.bin")
        with atomic_output(path) as tmp:
            with open(tmp, "wb") as f:
                f.write(b"payload")
        assert open(path, "rb").read() == b"payload"
        with pytest.raises(ValueError):
            with atomic_output(path) as tmp:
                with open(tmp, "wb") as f:
                    f.write(b"half-")
                raise ValueError("writer died")
        # target untouched, no temp litter
        assert open(path, "rb").read() == b"payload"
        assert [n for n in os.listdir(tmp_path) if ".tmp" in n] == []

    def test_atomic_output_sweeps_stale_tmps(self, tmp_path):
        path = str(tmp_path / "f.bin")
        stale = f"{path}.tmp99999"
        open(stale, "wb").write(b"orphan from a killed writer")
        with atomic_output(path) as tmp:
            open(tmp, "wb").write(b"x")
        assert not os.path.exists(stale)

    def _fake_snapshot(self, tmp_path, it, payload=b"weights"):
        prefix = str(tmp_path / "s")
        model = f"{prefix}_iter_{it}.caffemodel"
        state = f"{prefix}_iter_{it}.solverstate"
        open(model, "wb").write(payload + str(it).encode())
        open(state, "wb").write(b"state" + str(it).encode())
        write_snapshot_manifest(state, it, {"model": model, "state": state})
        return model, state

    def test_manifest_verify_and_corruption(self, tmp_path):
        model, state = self._fake_snapshot(tmp_path, 4)
        (it, mpath), = iter_snapshot_manifests(str(tmp_path / "s"))
        assert it == 4
        doc = verify_snapshot(mpath)
        assert doc is not None and doc["state"] == os.path.abspath(state)
        # flip one byte -> crc mismatch -> None
        b = bytearray(open(model, "rb").read())
        b[len(b) // 2] ^= 0xFF
        open(model, "wb").write(bytes(b))
        assert verify_snapshot(mpath) is None
        # truncation (size mismatch) also detected
        model2, _ = self._fake_snapshot(tmp_path, 8)
        open(model2, "wb").write(b"w")
        (_, mpath2), _ = iter_snapshot_manifests(str(tmp_path / "s"))
        assert verify_snapshot(mpath2) is None

    def test_gc_never_deletes_newest_verified(self, tmp_path):
        prefix = str(tmp_path / "s")
        for it in (2, 4, 6, 8):
            self._fake_snapshot(tmp_path, it)
        # corrupt the newest two: the newest VERIFIED is iter 4
        for it in (6, 8):
            p = f"{prefix}_iter_{it}.caffemodel"
            open(p, "ab").write(b"rot")
        gc_snapshots(prefix, keep=2)
        remaining = {it for it, _ in iter_snapshot_manifests(prefix)}
        # keep window = {8, 6}; iter 4 survives as the newest verified;
        # iter 2 swept
        assert remaining == {8, 6, 4}
        gc_snapshots(prefix, keep=1)
        remaining = {it for it, _ in iter_snapshot_manifests(prefix)}
        assert 4 in remaining and 2 not in remaining


# ---------------------------------------------------------------------------
# unit: fault plane / watchdog / retry
# ---------------------------------------------------------------------------

class TestFaultPlane:
    def test_count_skip_arg(self):
        fp = FaultPlane()
        fp.configure("site:2:1:arg")
        assert fp.fire("site") is None          # skipped
        assert fp.fire("site") == "arg"         # 1st fire
        assert fp.fire("other") is None
        assert fp.fire("site") == "arg"         # 2nd fire
        assert fp.fire("site") is None          # exhausted
        assert fp.fire("site") is None

    def test_threshold_key(self):
        fp = FaultPlane()
        fp.configure("abort:1::9")
        assert fp.fire("abort", key=5) is None
        assert fp.fire("abort", key=9) == "9"
        assert fp.fire("abort", key=10) is None  # exhausted

    def test_once_dir_disables_across_processes(self, tmp_path):
        d = str(tmp_path)
        fp = FaultPlane()
        fp.configure("boom:1", once_dir=d)
        assert fp.fire("boom") == ""
        assert os.path.exists(os.path.join(d, "boom.done"))
        fp2 = FaultPlane()  # "the restarted process"
        fp2.configure("boom:1", once_dir=d)
        assert fp2.fire("boom") is None

    def test_zero_cost_when_off(self):
        fp = FaultPlane()
        fp.configure("")
        assert fp.fire("anything") is None


class TestWatchdogRetry:
    def test_watchdog_trips_on_stuck_section(self):
        trips = []
        wd = DispatchWatchdog(0.2, lambda label, el: trips.append(label),
                              poll=0.05, hard_exit=False)
        try:
            with wd.section("dispatch"):
                assert wd.tripped_event.wait(3.0)
        finally:
            wd.stop()
        assert trips == ["dispatch"]
        assert wd.tripped[0] == "dispatch" and wd.tripped[1] > 0.2

    def test_watchdog_quiet_on_fast_sections(self):
        wd = DispatchWatchdog(0.5, poll=0.02, hard_exit=False)
        try:
            for _ in range(5):
                with wd.section("dispatch"):
                    time.sleep(0.01)
            time.sleep(0.1)
            assert wd.tripped is None
        finally:
            wd.stop()

    def test_retrying_bounded(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"
        assert retrying(flaky, attempts=4, base_delay=0.001) == "ok"
        assert len(calls) == 3

        hard = []

        def always_fails():
            hard.append(1)
            raise OSError("hard")
        with pytest.raises(OSError, match="hard"):
            retrying(always_fails, attempts=3, base_delay=0.001)
        assert len(hard) == 3  # bounded, not infinite


# ---------------------------------------------------------------------------
# unit: feeder retry + feed-queue error context
# ---------------------------------------------------------------------------

class _TinyDataset:
    def __init__(self, n=8):
        self.n = n

    def __len__(self):
        return self.n

    def get(self, i):
        img = np.full((1, 4, 4), i, np.uint8)
        return img, i % 4


class TestFeederFaults:
    def test_transient_read_retries(self):
        from caffe_mpi_tpu.data.feeder import Feeder
        resilience.FAULTS.configure("feeder_read:2")
        try:
            f = Feeder(_TinyDataset(), None, 4, threads=1)
            batch = f._build_batch_inner(0)
            assert batch["data"].shape == (4, 1, 4, 4)
            f.close()
        finally:
            resilience.FAULTS.configure("")

    def test_persistent_read_surfaces(self):
        from caffe_mpi_tpu.data.feeder import Feeder
        resilience.FAULTS.configure("feeder_read:99")
        try:
            f = Feeder(_TinyDataset(), None, 4, threads=1)
            with pytest.raises(OSError, match="injected dataset read"):
                f._build_batch_inner(0)
            f.close()
        finally:
            resilience.FAULTS.configure("")

    def test_feed_queue_names_failing_chunk(self):
        from caffe_mpi_tpu.data.feeder import DeviceFeedQueue, FeedError

        def bad_feed(it):
            raise OSError(f"disk gone at micro-iter {it}")
        q = DeviceFeedQueue(bad_feed)
        try:
            with pytest.raises(FeedError, match=r"it0=6, k=3"):
                q.get(6, 3)
        finally:
            q.close()


# ---------------------------------------------------------------------------
# solver-level: verified snapshots, GC knob, corruption fallback
# ---------------------------------------------------------------------------

LSQ_NET = """
name: "lsq"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 4 dim: 3 } shape { dim: 4 dim: 1 } } }
layer { name: "ip" type: "InnerProduct" bottom: "x" top: "pred"
        inner_product_param { num_output: 1
          weight_filler { type: "gaussian" std: 1 } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "pred" bottom: "t" top: "l" }
"""


def _make_solver(extra=""):
    sp = SolverParameter.from_text(
        f'base_lr: 0.1 max_iter: 50 lr_policy: "fixed" display: 0 '
        f'random_seed: 3\n{extra}')
    sp.net_param = NetParameter.from_text(LSQ_NET)
    return Solver(sp)


def _feeds(it):
    r = np.random.RandomState(it % 16)
    x = r.randn(4, 3).astype(np.float32)
    t = (x @ np.array([[1.0], [-2.0], [0.5]]) + 0.3).astype(np.float32)
    return {"x": jnp.asarray(x), "t": jnp.asarray(t)}


class TestSolverSnapshots:
    def test_snapshot_keep_gc_and_run_manifest(self, tmp_path):
        s = _make_solver("snapshot: 2 snapshot_keep: 2")
        s.sp.snapshot_prefix = str(tmp_path / "s")
        s.step(8, _feeds)
        s.close()
        its = [it for it, _ in iter_snapshot_manifests(str(tmp_path / "s"))]
        assert its == [8, 6]  # keep=2: older sets GC'd
        for _it, m in iter_snapshot_manifests(str(tmp_path / "s")):
            assert verify_snapshot(m) is not None
        assert not os.path.exists(tmp_path / "s_iter_2.caffemodel")
        run = resilience.read_run_manifest(str(tmp_path / "s"))
        assert run["iter"] == 8 and run["reason"] == "snapshot"
        assert run["last_snapshot_state"].endswith("s_iter_8.solverstate")

    def test_restore_rejects_corrupt_and_auto_falls_back(self, tmp_path):
        ref = _make_solver("snapshot: 2")
        ref.sp.snapshot_prefix = str(tmp_path / "s")
        ref.step(6, _feeds)
        ref.close()
        final_w = np.asarray(ref.params["ip"]["weight"])
        # corrupt the newest model file (post-manifest bitrot)
        p = tmp_path / "s_iter_6.caffemodel"
        b = bytearray(p.read_bytes())
        b[len(b) // 2] ^= 0xFF
        p.write_bytes(bytes(b))

        fresh = _make_solver()
        fresh.sp.snapshot_prefix = str(tmp_path / "s")
        with pytest.raises(resilience.SnapshotCorruptError):
            fresh.restore(str(tmp_path / "s_iter_6.solverstate"))
        # auto-resume skips the corrupt 6 and lands on the verified 4,
        # replays 4..6 and must match the uninterrupted run bit-exactly
        state = fresh.restore_auto()
        assert state.endswith("s_iter_4.solverstate")
        assert fresh.iter == 4
        fresh.step(2, _feeds)
        fresh.close()
        assert np.array_equal(np.asarray(fresh.params["ip"]["weight"]),
                              final_w)

    def test_restore_auto_handles_legacy_unmanifested(self, tmp_path):
        ref = _make_solver()
        ref.sp.snapshot_prefix = str(tmp_path / "s")
        ref.step(3, _feeds)
        ref.snapshot()
        ref.close()
        # simulate a pre-ISSUE-3 snapshot: drop the manifest sidecar
        os.unlink(tmp_path / "s_iter_3.manifest.json")
        fresh = _make_solver()
        fresh.sp.snapshot_prefix = str(tmp_path / "s")
        assert fresh.restore_auto().endswith("s_iter_3.solverstate")
        assert fresh.iter == 3
        fresh.close()

    def test_restore_auto_empty_is_fresh_start(self, tmp_path):
        s = _make_solver()
        s.sp.snapshot_prefix = str(tmp_path / "nothing" / "here")
        assert s.restore_auto() is None
        assert s.iter == 0
        s.close()


# ---------------------------------------------------------------------------
# e2e acceptance: CLI subprocesses, each fault ends in an auto-resume
# that is iteration-exact vs the uninterrupted baseline
# ---------------------------------------------------------------------------

def _build_workspace(root):
    """Tiny LMDB + prototxts shared by every scenario (snapshot prefix
    differs per scenario via -snapshot_prefix)."""
    from caffe_mpi_tpu.data.datasets import encode_datum
    from caffe_mpi_tpu.data.lmdb_io import write_lmdb
    os.makedirs(root, exist_ok=True)
    db = os.path.join(root, "train_lmdb")
    r = np.random.RandomState(7)
    write_lmdb(db, ((f"{i:08d}".encode(),
                     encode_datum(r.randint(0, 256, (1, 6, 6), np.uint8)
                                  .astype(np.uint8), int(i % 4)))
                    for i in range(16)))
    net = os.path.join(root, "net.prototxt")
    with open(net, "w") as f:
        f.write(f"""
name: "ftnet"
layer {{ name: "data" type: "Data" top: "data" top: "label"
        data_param {{ source: "{db}" batch_size: 4 backend: LMDB }} }}
layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "score"
        inner_product_param {{ num_output: 4
          weight_filler {{ type: "xavier" }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "score"
        bottom: "label" top: "loss" }}
""")
    solver = os.path.join(root, "solver.prototxt")
    with open(solver, "w") as f:
        f.write(f'net: "{net}"\nbase_lr: 0.05 momentum: 0.9\n'
                f'lr_policy: "fixed" max_iter: 12 random_seed: 3\n'
                f'display: 0 snapshot: 4\n')
    return solver


def _run_cli(solver, prefix, *extra, faults="", faults_dir="",
             timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=_ROOT, CAFFE_TPU_FAULTS=faults,
               CAFFE_TPU_FAULTS_DIR=faults_dir)
    env.pop("CAFFE_SUPERVISED_CHILD", None)
    cmd = [sys.executable, "-m", "caffe_mpi_tpu.tools.cli", "train",
           "-solver", solver, "-snapshot_prefix", prefix, *extra]
    return subprocess.run(cmd, env=env, cwd=_ROOT, timeout=timeout,
                          capture_output=True, text=True)


def _final_weights(prefix):
    from caffe_mpi_tpu.io import load_caffemodel
    path = f"{prefix}_iter_12.caffemodel"
    assert os.path.exists(path), f"missing final snapshot {path}"
    return load_caffemodel(path)


def _assert_bitwise_equal(got, want):
    assert set(got) == set(want)
    for lname in want:
        for a, b in zip(got[lname], want[lname]):
            assert np.array_equal(a, b), f"{lname}: weight bits differ"


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("fault_tolerance"))
    solver = _build_workspace(root)
    prefix = os.path.join(root, "baseline", "s")
    r = _run_cli(solver, prefix)
    assert r.returncode == 0, r.stderr[-2000:]
    return {"root": root, "solver": solver,
            "baseline": _final_weights(prefix)}


def _scenario(ws, name, faults, *extra):
    root = ws["root"]
    prefix = os.path.join(root, name, "s")
    fdir = os.path.join(root, name + "_faults")
    os.makedirs(fdir, exist_ok=True)
    r = _run_cli(ws["solver"], prefix, *extra, faults=faults,
                 faults_dir=fdir)
    assert r.returncode == 0, \
        f"{name}: rc={r.returncode}\n{r.stdout[-1500:]}\n{r.stderr[-1500:]}"
    _assert_bitwise_equal(_final_weights(prefix), ws["baseline"])
    return r


class TestEndToEndRecovery:
    def test_transient_feeder_error_absorbed(self, ws):
        """2 injected read failures < the 4-attempt retry budget: the
        run completes with NO restart, iteration-exact."""
        r = _scenario(ws, "feed_transient", "feeder_read:2")
        assert "supervisor" not in r.stderr  # absorbed in-process

    def test_fatal_feeder_error_restarts(self, ws):
        """A sticky read failure (the dataset is gone, not one blip)
        exhausts the retry budget; the child dies, the supervisor
        restarts it from the newest verified snapshot, and the final
        bits match the uninterrupted run."""
        r = _scenario(ws, "feed_fatal", "feeder_read:-1",
                      "-max_restarts", "2")
        assert "restarting from the newest verified snapshot" in r.stderr

    def test_kill_mid_snapshot_write(self, ws):
        """Process dies INSIDE the snapshot-8 write (after the model
        file, before state+manifest; snapshot_sync pins the write to
        the iteration boundary): the half-written snapshot is invisible
        to resume, the previous one (iter 4) loads, and the replayed
        run is bit-exact."""
        r = _scenario(ws, "kill_mid_write",
                      "snapshot_sync:-1,snapshot_kill:1:1",
                      "-max_restarts", "2")
        assert "restarting from the newest verified snapshot" in r.stderr
        assert "Restored solver state" in r.stderr
        assert "s_iter_4.solverstate" in r.stderr

    def test_corrupted_snapshot_falls_back(self, ws):
        """Snapshot 8 is corrupted after its manifest lands (bitrot;
        snapshot_sync makes the write order deterministic); the child
        then dies at iter 10. Resume detects the crc mismatch, falls
        back to the verified iter-4 snapshot, and replays to an
        identical result."""
        r = _scenario(ws, "corrupt",
                      "snapshot_sync:-1,snapshot_corrupt:1:1,"
                      "train_abort:1:0:10", "-max_restarts", "2")
        assert "failed crc verification" in r.stderr
        assert "s_iter_4.solverstate" in r.stderr

    def test_dispatch_stall_watchdog_resume(self, ws):
        """A 12s stall inside a train dispatch vs a 3s watchdog
        deadline: the monitor journals the run state, hard-exits 86,
        and the supervisor auto-resumes to a bit-exact finish."""
        r = _scenario(ws, "stall", "dispatch_stall:1:6:12",
                      "-max_restarts", "2", "-watchdog_deadline", "3")
        assert "exceeded 3.0s deadline" in r.stderr
        assert "supervisor: child failed (watchdog)" in r.stderr
        # the watchdog journaled before dying
        run = resilience.read_run_manifest(
            os.path.join(ws["root"], "stall", "s"))
        assert run is not None  # rewritten by the recovered run
        fail_log = os.path.join(ws["root"], "stall", "s.failures.log")
        assert os.path.exists(fail_log)
        assert "watchdog" in open(fail_log).read()

    def test_crash_loop_guard_gives_up(self, ws):
        """Unrecoverable fault (refires every restart: no once-marker
        dir): the supervisor stops after N restarts, preserving the
        failure log, instead of looping forever."""
        root = ws["root"]
        prefix = os.path.join(root, "crashloop", "s")
        r = _run_cli(ws["solver"], prefix, "-max_restarts", "1",
                     faults="train_abort:99:0:2")  # no faults_dir
        assert r.returncode == resilience.EXIT_FAULT
        assert "crash-loop guard" in r.stderr
        log = prefix + ".failures.log"
        assert os.path.exists(log)
        assert len(open(log).read().splitlines()) >= 2
