"""Data pipeline tests — mirrors reference test_data_layer.cpp /
test_data_transformer.cpp / test_db.cpp: on-the-fly fixtures, transform
semantics, deterministic rank partitioning, and binaryproto/caffemodel I/O.
"""

import os
import struct

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.data import (
    CIFAR10Dataset,
    DataTransformer,
    Feeder,
    ImageFolderDataset,
    MNISTDataset,
    SyntheticDataset,
    encode_datum,
    parse_datum,
)
from caffe_mpi_tpu.io import (
    encode_blob,
    load_blob_binaryproto,
    parse_blob,
    parse_caffemodel,
    encode_caffemodel,
    save_blob_binaryproto,
)
from caffe_mpi_tpu.proto import TransformationParameter


class TestDatum:
    def test_roundtrip(self):
        img = np.arange(2 * 3 * 4, dtype=np.uint8).reshape(2, 3, 4)
        buf = encode_datum(img, 7)
        arr, label = parse_datum(buf)
        np.testing.assert_array_equal(arr, img)
        assert label == 7


class TestBinaryProto:
    def test_blob_roundtrip(self, tmp_path):
        arr = np.random.RandomState(0).randn(3, 4, 5).astype(np.float32)
        p = str(tmp_path / "mean.binaryproto")
        save_blob_binaryproto(p, arr)
        back = load_blob_binaryproto(p)
        np.testing.assert_array_equal(back, arr)

    def test_caffemodel_roundtrip(self):
        w = {
            "conv1": [np.random.rand(4, 3, 3, 3).astype(np.float32),
                      np.random.rand(4).astype(np.float32)],
            "fc": [np.random.rand(10, 8).astype(np.float32)],
        }
        buf = encode_caffemodel(w, "testnet", {"conv1": "Convolution"})
        back = parse_caffemodel(buf)
        assert set(back) == {"conv1", "fc"}
        for k in w:
            for a, b in zip(w[k], back[k]):
                np.testing.assert_array_equal(a, b)

    def test_fp16_raw_blob(self):
        # NVCaffe raw fp16 storage (caffe.proto raw_data_type/raw_data)
        vals = np.array([1.5, -2.25, 0.125], np.float16)

        def varint(v):
            out = bytearray()
            while True:
                if v < 0x80:
                    out.append(v)
                    return bytes(out)
                out.append((v & 0x7F) | 0x80)
                v >>= 7

        dims = varint(3)
        shape_msg = bytes([0x0A]) + varint(len(dims)) + dims  # field1 wire2
        buf = (bytes([0x3A]) + varint(len(shape_msg)) + shape_msg  # shape=7
               + bytes([0x50]) + varint(2)  # raw_data_type=10 -> FLOAT16
               + bytes([0x62]) + varint(6) + vals.tobytes())  # raw_data=12
        arr = parse_blob(buf)
        np.testing.assert_array_equal(arr, vals.astype(np.float32))


class TestDatasets:
    def test_mnist_idx(self, tmp_path):
        imgs = np.random.RandomState(0).randint(0, 256, (5, 28, 28)).astype(np.uint8)
        labels = np.arange(5, dtype=np.uint8)
        ip, lp = str(tmp_path / "img"), str(tmp_path / "lab")
        with open(ip, "wb") as f:
            f.write(struct.pack(">IIII", 2051, 5, 28, 28) + imgs.tobytes())
        with open(lp, "wb") as f:
            f.write(struct.pack(">II", 2049, 5) + labels.tobytes())
        ds = MNISTDataset(ip, lp)
        assert len(ds) == 5
        img, lab = ds.get(3)
        assert img.shape == (1, 28, 28) and lab == 3
        np.testing.assert_array_equal(img[0], imgs[3])

    def test_cifar_binary(self, tmp_path):
        r = np.random.RandomState(1)
        recs = []
        for i in range(4):
            recs.append(bytes([i]) + r.randint(0, 256, 3072).astype(np.uint8).tobytes())
        p = str(tmp_path / "data_batch_1.bin")
        with open(p, "wb") as f:
            f.write(b"".join(recs))
        ds = CIFAR10Dataset(p)
        assert len(ds) == 4
        img, lab = ds.get(2)
        assert img.shape == (3, 32, 32) and lab == 2

    def test_image_folder(self, tmp_path):
        from PIL import Image
        r = np.random.RandomState(2)
        lines = []
        for i in range(3):
            arr = r.randint(0, 256, (10, 12, 3)).astype(np.uint8)
            Image.fromarray(arr).save(tmp_path / f"im{i}.png")
            lines.append(f"im{i}.png {i}")
        src = tmp_path / "index.txt"
        src.write_text("\n".join(lines))
        ds = ImageFolderDataset(str(src), root=str(tmp_path),
                                new_height=8, new_width=8)
        img, lab = ds.get(1)
        assert img.shape == (3, 8, 8) and lab == 1


class TestLMDB:
    """The dependency-free LMDB B+tree reader/writer (data/lmdb_io.py),
    mirroring reference test_db.cpp: build a fixture DB on the fly, walk it
    with a cursor, point-look-up keys. No third-party lmdb import anywhere."""

    def _roundtrip(self, tmp_path, items, **kw):
        from caffe_mpi_tpu.data.lmdb_io import LMDBReader, write_lmdb
        path = str(tmp_path / "db")
        write_lmdb(path, items, **kw)
        with LMDBReader(path) as r:
            assert len(r) == len(items)
            got = list(r.items())
        want = sorted(items, key=lambda kv: kv[0])
        assert [k for k, _ in got] == [k for k, _ in want]
        assert [v for _, v in got] == [v for _, v in want]
        with LMDBReader(path) as r:
            for k, v in want:
                assert r.get(k) == v
            assert r.get(b"\xffnope") is None
        return path

    def test_single_leaf(self, tmp_path):
        items = [(f"{i:08d}".encode(), f"value-{i}".encode())
                 for i in range(10)]
        self._roundtrip(tmp_path, items)

    def test_multi_level_tree(self, tmp_path):
        # ~66-byte nodes -> ~50/leaf -> 3000 records forces depth >= 3
        items = [(f"{i:08d}".encode(), (f"v{i}" * 10).encode())
                 for i in range(3000)]
        self._roundtrip(tmp_path, items)

    def test_overflow_values(self, tmp_path):
        # values over the ~2KB node budget go to F_BIGDATA overflow chains
        rng = np.random.RandomState(3)
        items = [(f"{i:04d}".encode(),
                  rng.bytes(sz))
                 for i, sz in enumerate([10, 3000, 5000, 100, 4096, 9000])]
        self._roundtrip(tmp_path, items)

    def test_truncated_overflow_value_raises(self, tmp_path):
        """A multi-page overflow value in a truncated file must raise, not
        silently return clamped bytes (mirrors lmdb_reader.cc's
        full-extent check)."""
        from caffe_mpi_tpu.data.lmdb_io import LMDBError, LMDBReader, \
            write_lmdb
        path = str(tmp_path / "db")
        big = bytes(range(256)) * 64              # 16KB -> several pages
        write_lmdb(path, [(b"big", big), (b"tiny", b"v")])
        data = tmp_path / "db" / "data.mdb"
        # chop the tail of the overflow chain but keep the meta/leaf pages
        data.write_bytes(data.read_bytes()[:-8192])
        with LMDBReader(path) as r:
            with pytest.raises(LMDBError, match="beyond EOF"):
                r.get(b"big")

    def test_empty_db(self, tmp_path):
        from caffe_mpi_tpu.data.lmdb_io import LMDBReader, write_lmdb
        path = str(tmp_path / "db")
        write_lmdb(path, [])
        with LMDBReader(path) as r:
            assert len(r) == 0
            assert list(r.items()) == []
            assert r.get(b"x") is None

    def test_nosubdir_file(self, tmp_path):
        from caffe_mpi_tpu.data.lmdb_io import LMDBReader, write_lmdb
        path = str(tmp_path / "flat.mdb")
        write_lmdb(path, [(b"k", b"v")], subdir=False)
        with LMDBReader(path) as r:
            assert r.get(b"k") == b"v"

    def test_bad_magic_rejected(self, tmp_path):
        from caffe_mpi_tpu.data.lmdb_io import LMDBError, LMDBReader
        p = tmp_path / "junk"
        p.mkdir()
        (p / "data.mdb").write_bytes(b"\x00" * 8192)
        with pytest.raises(LMDBError):
            LMDBReader(str(p))

    def test_on_disk_layout_matches_mdb_c(self, tmp_path):
        """Byte-level check of the emitted file against offsets hard-coded
        straight from mdb.c's struct definitions (NOT via lmdb_io's own
        constants) — catches the reader and writer sharing one mistake."""
        from caffe_mpi_tpu.data.lmdb_io import write_lmdb
        path = write_lmdb(str(tmp_path / "db"), [(b"abc", b"de")])
        raw = open(path, "rb").read()
        # MDB_page header: u64 pgno, u16 pad, u16 flags(P_META=0x08),
        # u16 lower, u16 upper; PAGEHDRSZ == 16
        assert struct.unpack_from("<Q", raw, 0)[0] == 0          # meta0 pgno
        assert struct.unpack_from("<H", raw, 10)[0] & 0x08       # P_META
        # MDB_meta at +16: mm_magic, mm_version
        assert struct.unpack_from("<I", raw, 16)[0] == 0xBEEFC0DE
        assert struct.unpack_from("<I", raw, 20)[0] == 1         # data ver
        # mm_dbs[0].md_pad at +16+24 carries the page size (mm_psize)
        assert struct.unpack_from("<I", raw, 40)[0] == 4096
        # mm_dbs[1] (main) at +16+24+48: md_depth at +8, md_entries at +32,
        # md_root at +40
        main = 16 + 24 + 48
        assert struct.unpack_from("<H", raw, main + 6)[0] == 1   # depth
        assert struct.unpack_from("<Q", raw, main + 32)[0] == 1  # entries
        root = struct.unpack_from("<Q", raw, main + 40)[0]
        assert root == 2
        # meta1 at offset psize, txnid at meta base + 24+48*2+8
        assert struct.unpack_from("<Q", raw, 4096 + 16 + 128)[0] == 1
        # root leaf page: flags has P_LEAF=0x02; one node; node at ptrs[0]:
        # u16 lo(dsize)=2, u16 hi=0, u16 flags=0, u16 ksize=3, "abc", "de"
        off = root * 4096
        assert struct.unpack_from("<H", raw, off + 10)[0] & 0x02
        lower, upper = struct.unpack_from("<HH", raw, off + 12)
        assert (lower - 16) >> 1 == 1                            # NUMKEYS
        (ptr,) = struct.unpack_from("<H", raw, off + 16)
        assert ptr == upper
        lo, hi, nflags, ksize = struct.unpack_from("<HHHH", raw, off + ptr)
        assert (lo | hi << 16, nflags, ksize) == (2, 0, 3)
        assert raw[off + ptr + 8: off + ptr + 13] == b"abcde"

    def test_datum_lmdb_dataset(self, tmp_path):
        """A Datum LMDB round-trips through LMDBDataset with no
        third-party import (the reference data_layer path, db_lmdb.cpp)."""
        from caffe_mpi_tpu.data.datasets import LMDBDataset
        from caffe_mpi_tpu.data.lmdb_io import write_lmdb
        rng = np.random.RandomState(7)
        imgs = rng.randint(0, 256, (5, 3, 6, 4), dtype=np.uint8)
        labels = [3, 1, 4, 1, 5]
        items = [(f"{i:08d}".encode(), encode_datum(imgs[i], labels[i]))
                 for i in range(5)]
        path = str(tmp_path / "datums")
        write_lmdb(path, items)
        ds = LMDBDataset(path)
        assert len(ds) == 5
        for i in range(5):
            arr, lab = ds.get(i)
            np.testing.assert_array_equal(arr, imgs[i])
            assert lab == labels[i]

    def test_convert_imageset_lmdb_backend(self, tmp_path):
        """convert_imageset -backend lmdb works without the lmdb module and
        the result feeds LMDBDataset (reference tools/convert_imageset.cpp)."""
        from PIL import Image
        from caffe_mpi_tpu.data.datasets import LMDBDataset
        from caffe_mpi_tpu.tools.convert_imageset import main as convert_main
        rng = np.random.RandomState(11)
        img_dir = tmp_path / "imgs"
        img_dir.mkdir()
        lines = []
        for i in range(4):
            arr = rng.randint(0, 256, (5, 7, 3), dtype=np.uint8)
            Image.fromarray(arr).save(img_dir / f"im{i}.png")
            lines.append(f"im{i}.png {i % 2}")
        listfile = tmp_path / "list.txt"
        listfile.write_text("\n".join(lines) + "\n")
        db = str(tmp_path / "out_lmdb")
        assert convert_main([str(img_dir), str(listfile), db]) == 0
        ds = LMDBDataset(db)
        assert len(ds) == 4
        arr, lab = ds.get(2)
        assert arr.shape == (3, 5, 7) and lab == 0


class TestLevelDB:
    """Dependency-free SSTable reader (data/leveldb_io.py): prefix
    compression, multi-block tables, snappy blocks, sequence/deletion
    semantics, Datum integration."""

    def test_roundtrip_multiblock_prefix_compressed(self, tmp_path):
        from caffe_mpi_tpu.data.leveldb_io import LevelDBReader, write_leveldb
        items = [(f"{i:08d}_record".encode(), (f"payload-{i}" * 7).encode())
                 for i in range(500)]  # forces several 4KB blocks + restarts
        path = write_leveldb(str(tmp_path / "db"), items)
        r = LevelDBReader(path)
        assert len(r) == 500
        got = list(r.items())
        assert got == sorted(items)
        assert r.get(b"00000042_record") == items[42][1]
        assert r.get(b"nope") is None

    def test_snappy_blocks(self, tmp_path):
        from caffe_mpi_tpu.data.leveldb_io import LevelDBReader, write_leveldb
        items = [(f"k{i:04d}".encode(), bytes([i % 256]) * 300)
                 for i in range(100)]
        path = write_leveldb(str(tmp_path / "db"), items, compress=True)
        assert list(LevelDBReader(path).items()) == sorted(items)

    def test_snappy_decoder_copies(self):
        """Hand-crafted snappy stream with all three copy-tag kinds (the
        literal-only fixture encoder never emits them)."""
        from caffe_mpi_tpu.data.leveldb_io import (snappy_compress_literal,
                                                   snappy_decompress)
        # "abcdabcdabcd": literal "abcd" + copy(offset=4, len=8)
        stream = bytes([12]) + bytes([3 << 2]) + b"abcd" \
            + bytes([((8 - 4) << 2) | 1 | (0 << 5), 4])
        assert snappy_decompress(stream) == b"abcdabcdabcd"
        # 2-byte-offset copy: literal x26 then copy(offset=26, len=26)
        lit = bytes(range(65, 91))
        stream2 = (bytes([52]) + bytes([25 << 2]) + lit
                   + bytes([((26 - 1) << 2) | 2]) + (26).to_bytes(2, "little"))
        assert snappy_decompress(stream2) == lit + lit
        # round-trip through the literal encoder
        data = b"x" * 100000 + b"tail"
        assert snappy_decompress(snappy_compress_literal(data)) == data

    def test_newest_sequence_wins_and_deletions_hide(self, tmp_path):
        """Two tables: newer sequence overrides; a tombstone hides the
        key (leveldb merge semantics the reference cursor sees)."""
        import struct as _s
        from caffe_mpi_tpu.data.leveldb_io import (LevelDBReader,
                                                   TYPE_DELETION, write_leveldb)
        path = write_leveldb(str(tmp_path / "db"),
                             [(b"a", b"old"), (b"b", b"keep"),
                              (b"c", b"dead")])
        # hand-build a second table with higher sequences: a->new, c deleted
        from caffe_mpi_tpu.data import leveldb_io as L
        table = bytearray()
        b = L._BlockBuilder()
        b.add(b"a" + _s.pack("<Q", (100 << 8) | 1), b"new")
        b.add(b"c" + _s.pack("<Q", (101 << 8) | TYPE_DELETION), b"")
        # real masked crc32c trailers: the reader verifies every block
        # on read since ISSUE 4 (a real leveldb writer always stores
        # them; the old zeros here only passed because nothing checked)
        def emit(blk):
            off = len(table)
            table.extend(blk + bytes([0]) + _s.pack(
                "<I", L.masked_crc32c(blk + bytes([0]))))
            return L._put_uvarint(off) + L._put_uvarint(len(blk))

        h = emit(b.finish())
        mih = emit(L._BlockBuilder().finish())
        ib = L._BlockBuilder()
        ib.add(b.last_key, h)
        ibh = emit(ib.finish())
        footer = mih + ibh
        footer += b"\x00" * (40 - len(footer)) + _s.pack("<Q", L.TABLE_MAGIC)
        table += footer
        with open(f"{path}/000007.ldb", "wb") as f:
            f.write(bytes(table))
        r = LevelDBReader(path)
        assert dict(r.items()) == {b"a": b"new", b"b": b"keep"}

    def test_crc32c_known_answers(self):
        """crc32c + leveldb mask against published test vectors (rfc3720 /
        leveldb crc32c_test.cc)."""
        from caffe_mpi_tpu.data.leveldb_io import crc32c, masked_crc32c
        assert crc32c(b"123456789") == 0xE3069283      # rfc3720 check value
        assert crc32c(b"\x00" * 32) == 0x8A9136AA      # crc32c_test.cc
        assert crc32c(b"\xff" * 32) == 0x62A8AB43
        # mask formula: rot15 + constant
        c = crc32c(b"foo")
        assert masked_crc32c(b"foo") == (
            (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF)

    def test_wal_torn_tail_keeps_valid_prefix(self, tmp_path):
        """A torn/corrupt final WAL record (writer crashed mid-append) is
        dropped and the valid prefix kept — real leveldb recovery
        semantics, not an error."""
        from caffe_mpi_tpu.data.leveldb_io import LevelDBReader, write_wal
        d = tmp_path / "db"
        d.mkdir()
        wal = d / "000003.log"
        write_wal(str(wal), [(b"a", b"1"), (b"b", b"2")])
        raw = bytearray(wal.read_bytes())
        raw[-1] ^= 0xFF  # corrupt the last record's payload
        wal.write_bytes(bytes(raw))
        r = LevelDBReader(str(d))
        assert dict(r.items()) == {b"a": b"1"}  # prefix survives

    def test_crc32c_throughput_path(self):
        """The pure-Python slice-by-8 path (fallback when google_crc32c is
        absent) agrees with a plain per-byte oracle on odd lengths, and
        with the native path when present."""
        from caffe_mpi_tpu.data.leveldb_io import _crc32c_py, crc32c
        rng = np.random.RandomState(0)
        for ln in (0, 1, 7, 8, 9, 63, 1000):
            data = rng.bytes(ln)
            poly, crc = 0x82F63B78, 0xFFFFFFFF
            for b in data:
                crc ^= b
                for _ in range(8):
                    crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            expect = crc ^ 0xFFFFFFFF
            assert _crc32c_py(data) == expect, ln
            assert crc32c(data) == expect, ln

    def test_wal_tail_replayed(self, tmp_path):
        """Real leveldb keeps the newest records ONLY in the NNNNNN.log
        write-ahead file until a memtable flush; the reader must replay it
        (log_format.h record framing + WriteBatch decode)."""
        from caffe_mpi_tpu.data.leveldb_io import LevelDBReader, write_leveldb
        items = [(f"{i:06d}".encode(), f"v{i}".encode()) for i in range(50)]
        path = write_leveldb(str(tmp_path / "db"), items, wal_tail=13)
        r = LevelDBReader(path)
        assert len(r) == 50
        assert list(r.items()) == sorted(items)
        assert r.get(b"000049") == b"v49"  # WAL-resident record

    def test_wal_only_db(self, tmp_path):
        """A small dataset that never flushed has NO .ldb files — still a
        valid DB (everything in the WAL)."""
        from caffe_mpi_tpu.data.leveldb_io import LevelDBReader, write_wal
        import os
        d = tmp_path / "db"
        d.mkdir()
        write_wal(str(d / "000003.log"),
                  [(b"a", b"1"), (b"b", b"2" * 40000)])  # multi-block record
        r = LevelDBReader(str(d))
        assert dict(r.items()) == {b"a": b"1", b"b": b"2" * 40000}
        assert not [f for f in os.listdir(d) if f.endswith(".ldb")]

    def test_datum_leveldb_dataset(self, tmp_path):
        from caffe_mpi_tpu.data.datasets import LevelDBDataset
        from caffe_mpi_tpu.data.leveldb_io import write_leveldb
        rng = np.random.RandomState(3)
        imgs = rng.randint(0, 256, (4, 3, 5, 5), dtype=np.uint8)
        labels = [2, 7, 1, 8]
        path = write_leveldb(
            str(tmp_path / "datums"),
            [(f"{i:08d}".encode(), encode_datum(imgs[i], labels[i]))
             for i in range(4)], compress=True)
        ds = LevelDBDataset(path)
        assert len(ds) == 4
        for i in range(4):
            arr, lab = ds.get(i)
            np.testing.assert_array_equal(arr, imgs[i])
            assert lab == labels[i]


class TestHDF5Feeder:
    """Streaming file-at-a-time HDF5 feeding (reference hdf5_data_layer.cpp
    LoadHDF5FileData semantics: bounded memory, per-epoch file shuffle)."""

    def _make_source(self, tmp_path, sizes=(5, 7, 4)):
        import h5py
        paths = []
        base = 0
        for i, n in enumerate(sizes):
            p = tmp_path / f"part{i}.h5"
            with h5py.File(p, "w") as f:
                f["data"] = np.arange(base, base + n,
                                      dtype=np.float32).reshape(n, 1)
                f["label"] = np.arange(base, base + n, dtype=np.int64)
            base += n
            paths.append(p.name)
        src = tmp_path / "source.txt"
        src.write_text("\n".join(paths) + "\n")
        return str(src)

    def _feeder(self, tmp_path, batch=4, shuffle=False, **kw):
        from caffe_mpi_tpu.data.feeder import HDF5Feeder
        from caffe_mpi_tpu.proto import NetParameter
        src = self._make_source(tmp_path)
        lp = NetParameter.from_text(f"""
            layer {{ name: "h" type: "HDF5Data" top: "data" top: "label"
                    hdf5_data_param {{ source: "{src}" batch_size: {batch}
                                       shuffle: {'true' if shuffle else 'false'} }} }}
        """).layer[0]
        return HDF5Feeder(lp, **kw)

    def test_epoch_covers_all_rows_in_file_order(self, tmp_path):
        f = self._feeder(tmp_path, batch=4)
        seen = []
        for it in range(4):  # 16 = one epoch fits exactly
            seen.extend(f(it)["label"].tolist())
        assert seen == list(range(16))  # file order, row order
        # second epoch repeats
        assert f(4)["label"].tolist() == [0, 1, 2, 3]

    def test_cache_bounded_to_two_files(self, tmp_path):
        f = self._feeder(tmp_path, batch=4)
        for it in range(8):
            f(it)
            assert len(f._cache) <= 2

    def test_shuffle_deterministic_and_complete(self, tmp_path):
        f1 = self._feeder(tmp_path, batch=4, shuffle=True)
        f2 = self._feeder(tmp_path, batch=4, shuffle=True)
        e1 = [x for it in range(4) for x in f1(it)["label"].tolist()]
        e2 = [x for it in range(4) for x in f2(it)["label"].tolist()]
        assert e1 == e2                      # seed-deterministic
        assert sorted(e1) == list(range(16))  # full coverage
        next_epoch = [x for it in range(4, 8)
                      for x in f1(it)["label"].tolist()]
        assert sorted(next_epoch) == list(range(16))
        assert next_epoch != e1              # re-shuffled per epoch

    def test_rank_striping_disjoint(self, tmp_path):
        f0 = self._feeder(tmp_path, batch=4, rank=0, world=2)
        f1 = self._feeder(tmp_path, batch=4, rank=1, world=2)
        a = f0(0)["label"].tolist()
        b = f1(0)["label"].tolist()
        assert not set(a) & set(b)
        assert a + b == list(range(8))

    def test_mixed_dtype_files_rejected_at_init(self, tmp_path):
        import h5py
        from caffe_mpi_tpu.data.feeder import HDF5Feeder
        from caffe_mpi_tpu.proto import NetParameter
        with h5py.File(tmp_path / "a.h5", "w") as f:
            f["data"] = np.zeros((4, 2), np.float32)
            f["label"] = np.zeros(4, np.int64)
        with h5py.File(tmp_path / "b.h5", "w") as f:
            f["data"] = np.zeros((4, 2), np.float64)  # dtype differs
            f["label"] = np.zeros(4, np.int64)
        src = tmp_path / "s.txt"
        src.write_text("a.h5\nb.h5\n")
        lp = NetParameter.from_text(f"""
            layer {{ name: "h" type: "HDF5Data" top: "data" top: "label"
                    hdf5_data_param {{ source: "{src}" batch_size: 2 }} }}
        """).layer[0]
        with pytest.raises(ValueError, match="differs from first"):
            HDF5Feeder(lp)

    def test_data_rows_match_labels(self, tmp_path):
        f = self._feeder(tmp_path, batch=6, shuffle=True)
        out = f(0)
        np.testing.assert_array_equal(out["data"][:, 0],
                                      out["label"].astype(np.float32))


class TestTransformer:
    def test_scale_mean_value(self):
        tp = TransformationParameter.from_text(
            "scale: 0.5 mean_value: 10 mean_value: 20 mean_value: 30")
        tf = DataTransformer(tp, "TEST")
        img = np.full((3, 4, 4), 40, np.uint8)
        out = tf(img)
        np.testing.assert_allclose(out[0], (40 - 10) * 0.5)
        np.testing.assert_allclose(out[2], (40 - 30) * 0.5)

    def test_center_vs_random_crop(self):
        tp = TransformationParameter.from_text("crop_size: 2")
        img = np.arange(16, dtype=np.uint8).reshape(1, 4, 4)
        out_test = DataTransformer(tp, "TEST")(img)
        np.testing.assert_array_equal(out_test[0],
                                      [[5, 6], [9, 10]])  # center
        tf_train = DataTransformer(tp, "TRAIN", seed=0)
        crops = {tuple(tf_train(img).reshape(-1).astype(int)) for _ in range(30)}
        assert len(crops) > 1  # random crops differ

    def test_mirror(self):
        tp = TransformationParameter.from_text("mirror: true")
        img = np.arange(4, dtype=np.uint8).reshape(1, 1, 4)
        tf = DataTransformer(tp, "TRAIN", seed=3)
        outs = {tuple(tf(img).reshape(-1).astype(int)) for _ in range(20)}
        assert (0, 1, 2, 3) in outs and (3, 2, 1, 0) in outs

    def test_mean_file(self, tmp_path):
        mean = np.full((1, 4, 4), 7, np.float32)
        p = str(tmp_path / "m.binaryproto")
        save_blob_binaryproto(p, mean)
        tp = TransformationParameter.from_text(f'mean_file: "{p}"')
        out = DataTransformer(tp, "TEST")(np.full((1, 4, 4), 17, np.uint8))
        np.testing.assert_allclose(out, 10.0)


class TestFeeder:
    def test_rank_partitioning_disjoint(self):
        ds = SyntheticDataset(64, shape=(1, 4, 4))
        feeds = []
        for rank in range(4):
            f = Feeder(ds, None, batch_size=4, rank=rank, world=4, threads=1)
            feeds.append(f(0))
        labels = [tuple(f["label"].tolist()) for f in feeds]
        # ranks see disjoint, contiguous-striped records (CursorManager)
        flat = [l for ls in labels for l in ls]
        assert flat == [i % 10 for i in range(16)]

    def test_epoch_shuffle_deterministic(self):
        ds = SyntheticDataset(8, shape=(1, 2, 2))
        f1 = Feeder(ds, None, batch_size=4, shuffle=True, seed=5, threads=1)
        f2 = Feeder(ds, None, batch_size=4, shuffle=True, seed=5, threads=1)
        for it in range(4):
            np.testing.assert_array_equal(f1(it)["label"], f2(it)["label"])

    def test_trains_with_solver(self, tmp_path):
        from caffe_mpi_tpu.proto import NetParameter, SolverParameter
        from caffe_mpi_tpu.solver import Solver
        ds = SyntheticDataset(128, shape=(1, 8, 8), classes=4, noise=0.1)
        tf = DataTransformer(
            TransformationParameter.from_text("scale: 0.00390625"), "TRAIN")
        feeder = Feeder(ds, tf, batch_size=16, threads=2)
        # snapshot_prefix pinned to tmp: solve() snapshots after train,
        # and the default "snapshot" prefix litters the repo root
        sp = SolverParameter.from_text(
            'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" max_iter: 30 '
            f'type: "SGD" snapshot_prefix: "{tmp_path}/snap"')
        sp.net_param = NetParameter.from_text("""
        layer { name: "in" type: "Input" top: "data" top: "label"
                input_param { shape { dim: 16 dim: 1 dim: 8 dim: 8 }
                              shape { dim: 16 } } }
        layer { name: "ip" type: "InnerProduct" bottom: "data" top: "logits"
                inner_product_param { num_output: 4
                  weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
                bottom: "label" top: "loss" }
        """)
        solver = Solver(sp)
        loss = solver.solve(feeder)
        feeder.close()
        assert loss < 0.2
