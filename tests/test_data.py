"""Data pipeline tests — mirrors reference test_data_layer.cpp /
test_data_transformer.cpp / test_db.cpp: on-the-fly fixtures, transform
semantics, deterministic rank partitioning, and binaryproto/caffemodel I/O.
"""

import os
import struct

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.data import (
    CIFAR10Dataset,
    DataTransformer,
    Feeder,
    ImageFolderDataset,
    MNISTDataset,
    SyntheticDataset,
    encode_datum,
    parse_datum,
)
from caffe_mpi_tpu.io import (
    encode_blob,
    load_blob_binaryproto,
    parse_blob,
    parse_caffemodel,
    encode_caffemodel,
    save_blob_binaryproto,
)
from caffe_mpi_tpu.proto import TransformationParameter


class TestDatum:
    def test_roundtrip(self):
        img = np.arange(2 * 3 * 4, dtype=np.uint8).reshape(2, 3, 4)
        buf = encode_datum(img, 7)
        arr, label = parse_datum(buf)
        np.testing.assert_array_equal(arr, img)
        assert label == 7


class TestBinaryProto:
    def test_blob_roundtrip(self, tmp_path):
        arr = np.random.RandomState(0).randn(3, 4, 5).astype(np.float32)
        p = str(tmp_path / "mean.binaryproto")
        save_blob_binaryproto(p, arr)
        back = load_blob_binaryproto(p)
        np.testing.assert_array_equal(back, arr)

    def test_caffemodel_roundtrip(self):
        w = {
            "conv1": [np.random.rand(4, 3, 3, 3).astype(np.float32),
                      np.random.rand(4).astype(np.float32)],
            "fc": [np.random.rand(10, 8).astype(np.float32)],
        }
        buf = encode_caffemodel(w, "testnet", {"conv1": "Convolution"})
        back = parse_caffemodel(buf)
        assert set(back) == {"conv1", "fc"}
        for k in w:
            for a, b in zip(w[k], back[k]):
                np.testing.assert_array_equal(a, b)

    def test_fp16_raw_blob(self):
        # NVCaffe raw fp16 storage (caffe.proto raw_data_type/raw_data)
        vals = np.array([1.5, -2.25, 0.125], np.float16)

        def varint(v):
            out = bytearray()
            while True:
                if v < 0x80:
                    out.append(v)
                    return bytes(out)
                out.append((v & 0x7F) | 0x80)
                v >>= 7

        dims = varint(3)
        shape_msg = bytes([0x0A]) + varint(len(dims)) + dims  # field1 wire2
        buf = (bytes([0x3A]) + varint(len(shape_msg)) + shape_msg  # shape=7
               + bytes([0x50]) + varint(2)  # raw_data_type=10 -> FLOAT16
               + bytes([0x62]) + varint(6) + vals.tobytes())  # raw_data=12
        arr = parse_blob(buf)
        np.testing.assert_array_equal(arr, vals.astype(np.float32))


class TestDatasets:
    def test_mnist_idx(self, tmp_path):
        imgs = np.random.RandomState(0).randint(0, 256, (5, 28, 28)).astype(np.uint8)
        labels = np.arange(5, dtype=np.uint8)
        ip, lp = str(tmp_path / "img"), str(tmp_path / "lab")
        with open(ip, "wb") as f:
            f.write(struct.pack(">IIII", 2051, 5, 28, 28) + imgs.tobytes())
        with open(lp, "wb") as f:
            f.write(struct.pack(">II", 2049, 5) + labels.tobytes())
        ds = MNISTDataset(ip, lp)
        assert len(ds) == 5
        img, lab = ds.get(3)
        assert img.shape == (1, 28, 28) and lab == 3
        np.testing.assert_array_equal(img[0], imgs[3])

    def test_cifar_binary(self, tmp_path):
        r = np.random.RandomState(1)
        recs = []
        for i in range(4):
            recs.append(bytes([i]) + r.randint(0, 256, 3072).astype(np.uint8).tobytes())
        p = str(tmp_path / "data_batch_1.bin")
        with open(p, "wb") as f:
            f.write(b"".join(recs))
        ds = CIFAR10Dataset(p)
        assert len(ds) == 4
        img, lab = ds.get(2)
        assert img.shape == (3, 32, 32) and lab == 2

    def test_image_folder(self, tmp_path):
        from PIL import Image
        r = np.random.RandomState(2)
        lines = []
        for i in range(3):
            arr = r.randint(0, 256, (10, 12, 3)).astype(np.uint8)
            Image.fromarray(arr).save(tmp_path / f"im{i}.png")
            lines.append(f"im{i}.png {i}")
        src = tmp_path / "index.txt"
        src.write_text("\n".join(lines))
        ds = ImageFolderDataset(str(src), root=str(tmp_path),
                                new_height=8, new_width=8)
        img, lab = ds.get(1)
        assert img.shape == (3, 8, 8) and lab == 1


class TestTransformer:
    def test_scale_mean_value(self):
        tp = TransformationParameter.from_text(
            "scale: 0.5 mean_value: 10 mean_value: 20 mean_value: 30")
        tf = DataTransformer(tp, "TEST")
        img = np.full((3, 4, 4), 40, np.uint8)
        out = tf(img)
        np.testing.assert_allclose(out[0], (40 - 10) * 0.5)
        np.testing.assert_allclose(out[2], (40 - 30) * 0.5)

    def test_center_vs_random_crop(self):
        tp = TransformationParameter.from_text("crop_size: 2")
        img = np.arange(16, dtype=np.uint8).reshape(1, 4, 4)
        out_test = DataTransformer(tp, "TEST")(img)
        np.testing.assert_array_equal(out_test[0],
                                      [[5, 6], [9, 10]])  # center
        tf_train = DataTransformer(tp, "TRAIN", seed=0)
        crops = {tuple(tf_train(img).reshape(-1).astype(int)) for _ in range(30)}
        assert len(crops) > 1  # random crops differ

    def test_mirror(self):
        tp = TransformationParameter.from_text("mirror: true")
        img = np.arange(4, dtype=np.uint8).reshape(1, 1, 4)
        tf = DataTransformer(tp, "TRAIN", seed=3)
        outs = {tuple(tf(img).reshape(-1).astype(int)) for _ in range(20)}
        assert (0, 1, 2, 3) in outs and (3, 2, 1, 0) in outs

    def test_mean_file(self, tmp_path):
        mean = np.full((1, 4, 4), 7, np.float32)
        p = str(tmp_path / "m.binaryproto")
        save_blob_binaryproto(p, mean)
        tp = TransformationParameter.from_text(f'mean_file: "{p}"')
        out = DataTransformer(tp, "TEST")(np.full((1, 4, 4), 17, np.uint8))
        np.testing.assert_allclose(out, 10.0)


class TestFeeder:
    def test_rank_partitioning_disjoint(self):
        ds = SyntheticDataset(64, shape=(1, 4, 4))
        feeds = []
        for rank in range(4):
            f = Feeder(ds, None, batch_size=4, rank=rank, world=4, threads=1)
            feeds.append(f(0))
        labels = [tuple(f["label"].tolist()) for f in feeds]
        # ranks see disjoint, contiguous-striped records (CursorManager)
        flat = [l for ls in labels for l in ls]
        assert flat == [i % 10 for i in range(16)]

    def test_epoch_shuffle_deterministic(self):
        ds = SyntheticDataset(8, shape=(1, 2, 2))
        f1 = Feeder(ds, None, batch_size=4, shuffle=True, seed=5, threads=1)
        f2 = Feeder(ds, None, batch_size=4, shuffle=True, seed=5, threads=1)
        for it in range(4):
            np.testing.assert_array_equal(f1(it)["label"], f2(it)["label"])

    def test_trains_with_solver(self):
        from caffe_mpi_tpu.proto import NetParameter, SolverParameter
        from caffe_mpi_tpu.solver import Solver
        ds = SyntheticDataset(128, shape=(1, 8, 8), classes=4, noise=0.1)
        tf = DataTransformer(
            TransformationParameter.from_text("scale: 0.00390625"), "TRAIN")
        feeder = Feeder(ds, tf, batch_size=16, threads=2)
        sp = SolverParameter.from_text(
            'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" max_iter: 30 '
            'type: "SGD"')
        sp.net_param = NetParameter.from_text("""
        layer { name: "in" type: "Input" top: "data" top: "label"
                input_param { shape { dim: 16 dim: 1 dim: 8 dim: 8 }
                              shape { dim: 16 } } }
        layer { name: "ip" type: "InnerProduct" bottom: "data" top: "logits"
                inner_product_param { num_output: 4
                  weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
                bottom: "label" top: "loss" }
        """)
        solver = Solver(sp)
        loss = solver.solve(feeder)
        feeder.close()
        assert loss < 0.2
