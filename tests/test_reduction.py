"""Overlapped bucketed gradient reduction (ISSUE 6,
caffe_mpi_tpu/parallel/reduction.py — reference ReduceAndUpdate,
src/caffe/net.cpp:757-913).

The contract under test: `reduce_overlap` is an EXECUTION-SCHEDULE
knob, not a semantics knob — the shard_map step with per-bucket psums
must land on BITWISE-identical params and optimizer state (CPU
backend) vs the implicit GSPMD reduction, across step_chunk {1, K},
iter_size accumulation, global-norm clipping, and train_guard. Plus:
the bucket planner's ordering/sizing rules, the knob validation that
replaces the old accept-and-ignore, the net-compatibility fallback,
and the per-step collective count the MULTICHIP dryrun reports.
"""

import logging

import numpy as np
import pytest

from caffe_mpi_tpu.parallel import MeshPlan, reduction
from caffe_mpi_tpu.proto import SolverParameter
from caffe_mpi_tpu.proto.config import NetParameter
from caffe_mpi_tpu.solver import Solver

MLP_NET = """
name: "mlp"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 16 dim: 6 } shape { dim: 16 } } }
layer { name: "ip1" type: "InnerProduct" bottom: "x" top: "h"
        inner_product_param { num_output: 32
          weight_filler { type: "xavier" } } }
layer { name: "r" type: "ReLU" bottom: "h" top: "h" }
layer { name: "ip2" type: "InnerProduct" bottom: "h" top: "y"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t"
        top: "l" }
"""

BN_NET = """
name: "bn_net"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 16 dim: 4 dim: 4 dim: 4 }
                      shape { dim: 16 } } }
layer { name: "conv" type: "Convolution" bottom: "x" top: "c"
        convolution_param { num_output: 4 kernel_size: 3 pad: 1
          weight_filler { type: "msra" } } }
layer { name: "bn" type: "BatchNorm" bottom: "c" top: "c" }
layer { name: "ip" type: "InnerProduct" bottom: "c" top: "y"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t"
        top: "l" }
"""


def make_solver(extra: str = "", net: str = MLP_NET, mesh=None) -> Solver:
    sp = SolverParameter.from_text(
        f'base_lr: 0.1 momentum: 0.9 max_iter: 1000 lr_policy: "fixed" '
        f'display: 0 random_seed: 5\n{extra}')
    sp.net_param = NetParameter.from_text(net)
    return Solver(sp, mesh=mesh)


def mlp_data(rng, n=32):
    return [{"x": rng.randn(16, 6).astype(np.float32),
             "t": rng.randint(0, 4, 16)} for _ in range(n)]


def assert_bitwise(a: Solver, b: Solver):
    """Params AND optimizer slots must be byte-identical — the
    acceptance bar for the overlapped step on the CPU backend."""
    for ln in a.params:
        for pn in a.params[ln]:
            ea, eb = np.asarray(a.params[ln][pn]), np.asarray(
                b.params[ln][pn])
            assert np.array_equal(ea, eb), \
                f"params {ln}/{pn} differ (max " \
                f"{np.abs(ea - eb).max():.3e})"
    for ln in a.opt_state:
        for pn in a.opt_state[ln]:
            for si, (sa, sb) in enumerate(zip(a.opt_state[ln][pn],
                                              b.opt_state[ln][pn])):
                assert np.array_equal(np.asarray(sa), np.asarray(sb)), \
                    f"opt {ln}/{pn}[{si}] differs"


# ---------------------------------------------------------------------------
# Bucket planner
# ---------------------------------------------------------------------------

class TestPlanner:
    ENTRIES = [  # (layer, param, shape, dtype) already reverse-topo
        ("ip2", "weight", (4, 32), np.float32),   # 512 B
        ("ip2", "bias", (4,), np.float32),        # 16 B
        ("ip1", "weight", (32, 6), np.float32),   # 768 B
        ("ip1", "bias", (32,), np.float32),       # 128 B
    ]

    def test_count_mode_produces_k_contiguous_buckets(self):
        plan = reduction.plan_buckets(self.ENTRIES, n_buckets=3, n_data=8)
        assert len(plan.buckets) == 3
        # contiguity: concatenating the buckets reproduces the order
        flat = [e for b in plan.buckets for e in b.entries]
        assert flat == [(l, p) for (l, p, _, _) in self.ENTRIES]
        assert sum(plan.bucket_bytes) == 512 + 16 + 768 + 128
        assert plan.collectives_per_step == 3

    def test_reverse_topo_order_from_net(self):
        s = make_solver("reduce_overlap: true reduce_buckets: 2",
                        mesh=MeshPlan.data_parallel())
        order = [e[0] for b in s._reduction.buckets for e in b.entries]
        # backward produces ip2's grads before ip1's
        assert order.index("ip2") < order.index("ip1")
        assert set(order) == {"ip1", "ip2"}

    def test_more_buckets_than_params_caps_at_params(self):
        plan = reduction.plan_buckets(self.ENTRIES, n_buckets=64)
        assert len(plan.buckets) == 4  # one per param, never empty ones

    def test_byte_budget_mode(self):
        plan = reduction.plan_buckets(self.ENTRIES, bucket_bytes=600)
        # greedy: [512+16=528], [768 overflows alone], [128]
        assert [b.nbytes for b in plan.buckets] == [528, 768, 128]

    def test_single_oversized_param_gets_own_bucket_and_warns(self, caplog):
        with caplog.at_level(logging.WARNING,
                             "caffe_mpi_tpu.parallel.reduction"):
            plan = reduction.plan_buckets(self.ENTRIES, bucket_bytes=256)
        sizes = [b.nbytes for b in plan.buckets]
        assert 512 in sizes and 768 in sizes  # each oversized, alone
        assert any("exceeds the grad_bucket_mb budget" in r.message
                   for r in caplog.records)

    def test_dtype_change_splits_bucket(self):
        entries = [("a", "w", (8,), np.float32),
                   ("b", "w", (8,), np.float16),
                   ("c", "w", (8,), np.float16)]
        plan = reduction.plan_buckets(entries, n_buckets=1)
        assert [b.dtype for b in plan.buckets] == ["float32", "float16"]

    def test_zero_knobs_rejected(self):
        with pytest.raises(ValueError, match="n_buckets"):
            reduction.plan_buckets(self.ENTRIES)


# ---------------------------------------------------------------------------
# Knob validation — the old silent accept-and-ignore must be gone
# ---------------------------------------------------------------------------

class TestKnobValidation:
    def test_net_level_zero_reduce_buckets_rejected(self):
        sp = SolverParameter.from_text(
            'base_lr: 0.1 max_iter: 10 lr_policy: "fixed"')
        sp.net_param = NetParameter.from_text(
            MLP_NET.replace('name: "mlp"', 'name: "mlp"\nreduce_buckets: 0'))
        with pytest.raises(ValueError, match="reduce_buckets"):
            Solver(sp)

    @pytest.mark.parametrize("knob", ["reduce_buckets: 0",
                                      "reduce_buckets: -2",
                                      "grad_bucket_mb: 0",
                                      "grad_bucket_mb: -1.5"])
    def test_solver_level_zero_or_negative_rejected(self, knob):
        with pytest.raises(ValueError):
            make_solver(knob)

    def test_both_sizing_modes_rejected(self):
        with pytest.raises(ValueError, match="not both"):
            make_solver("reduce_buckets: 4 grad_bucket_mb: 8.0")

    def test_overlap_without_mesh_rejected(self):
        with pytest.raises(ValueError, match="mesh"):
            make_solver("reduce_overlap: true")

    def test_valid_net_level_default_flows_into_plan(self):
        s = make_solver("reduce_overlap: true",
                        mesh=MeshPlan.data_parallel())
        # net-level default (6) caps at the 4 params
        assert 1 <= len(s._reduction.buckets) <= 6
        assert s.reduction_stats()["mode"] == "bucketed"


# ---------------------------------------------------------------------------
# Fallback gate
# ---------------------------------------------------------------------------

class TestFallback:
    def test_batchnorm_net_falls_back_with_warning(self, caplog):
        with caplog.at_level(logging.WARNING, "caffe_mpi_tpu.solver"):
            s = make_solver("reduce_overlap: true", net=BN_NET,
                            mesh=MeshPlan.data_parallel())
        assert s._reduction is None
        stats = s.reduction_stats()
        assert stats["mode"] == "implicit"
        assert "BatchNorm" in stats["fallback_reason"]
        assert any("falling back" in r.message for r in caplog.records)

    def test_fallback_net_still_trains(self, rng):
        s = make_solver("reduce_overlap: true", net=BN_NET,
                        mesh=MeshPlan.data_parallel())
        data = {"x": rng.randn(16, 4, 4, 4).astype(np.float32),
                "t": rng.randint(0, 4, 16)}
        loss = s.step(2, lambda it: data)
        assert np.isfinite(loss)

    def test_ignore_label_valid_norm_falls_back(self):
        net = MLP_NET.replace(
            'bottom: "t"\n        top: "l"',
            'bottom: "t"\n        top: "l"\n'
            '        loss_param { ignore_label: -1 }')
        s = make_solver("reduce_overlap: true", net=net,
                        mesh=MeshPlan.data_parallel())
        assert s._reduction is None
        assert "ignore_label" in s.reduction_stats()["fallback_reason"]

    def test_unsupported_reason_passes_clean_net(self):
        s = make_solver()
        assert reduction.unsupported_reason(s.net) is None

    def test_single_device_data_axis_falls_back(self):
        # the reference's reduce thread is idle at solver_count 1
        # (net.cpp:757-913 never fires) — with one device on the 'data'
        # axis there is nothing to reduce, and falling back keeps the
        # n=1 step bitwise (no all-reduce exists in the implicit
        # program for the clip/guard fusion boundary to differ against)
        import jax
        s = make_solver("reduce_overlap: true",
                        mesh=MeshPlan.from_shape(
                            data=1, devices=jax.devices()[:1]))
        assert s._reduction is None
        assert "single device" in s.reduction_stats()["fallback_reason"]


# ---------------------------------------------------------------------------
# Bitwise equivalence vs the implicit reduction (the acceptance bar)
# ---------------------------------------------------------------------------

class TestEquivalence:
    @pytest.mark.parametrize("cfg", [
        "",
        "clip_gradients: 0.7",
        "step_chunk: 4 clip_gradients: 0.7",
        "step_chunk: 4 train_guard: true clip_gradients: 0.7",
        "iter_size: 2 clip_gradients: 0.7",
        "iter_size: 2 step_chunk: 3 train_guard: true",
    ])
    def test_bitwise_vs_implicit(self, rng, cfg):
        data = mlp_data(rng)
        a = make_solver(cfg, mesh=MeshPlan.data_parallel())
        b = make_solver(cfg + " reduce_overlap: true reduce_buckets: 3",
                        mesh=MeshPlan.data_parallel())
        assert b._reduction is not None, b._reduction_fallback
        a.step(8, lambda it: data[it % 32])
        b.step(8, lambda it: data[it % 32])
        assert_bitwise(a, b)

    def test_byte_budget_plan_matches_too(self, rng):
        data = mlp_data(rng)
        a = make_solver("clip_gradients: 0.5",
                        mesh=MeshPlan.data_parallel())
        b = make_solver("clip_gradients: 0.5 reduce_overlap: true "
                        "grad_bucket_mb: 0.0005",
                        mesh=MeshPlan.data_parallel())
        assert len(b._reduction.buckets) >= 2
        a.step(6, lambda it: data[it])
        b.step(6, lambda it: data[it])
        assert_bitwise(a, b)

    def test_adam_trajectory(self, rng):
        data = mlp_data(rng)
        cfg = 'type: "Adam" momentum: 0.9 momentum2: 0.999'
        a = make_solver(cfg, mesh=MeshPlan.data_parallel())
        b = make_solver(cfg + " reduce_overlap: true reduce_buckets: 2",
                        mesh=MeshPlan.data_parallel())
        a.step(6, lambda it: data[it])
        b.step(6, lambda it: data[it])
        assert_bitwise(a, b)


# ---------------------------------------------------------------------------
# Measurement surface (what bench.py / the MULTICHIP dryrun report)
# ---------------------------------------------------------------------------

class TestMeasurement:
    def test_bucketed_step_emits_at_least_bucket_count_collectives(
            self, rng):
        data = mlp_data(rng, 1)
        b = make_solver("reduce_overlap: true reduce_buckets: 3",
                        mesh=MeshPlan.data_parallel())
        stats = reduction.collective_stats(b.step_hlo_text(data[0]))
        assert stats["all_reduces"] >= 3, stats

    def test_collective_stats_counts_hlo_text(self):
        text = "\n".join([
            "%x = f32[8]{0} parameter(0)",
            "%ar = f32[8]{0} all-reduce(%x), replica_groups={}",
            "%y = f32[8]{0} add(%ar, %ar)",
            "%ar2 = f32[8]{0} all-reduce-start(%y)",
        ])
        stats = reduction.collective_stats(text)
        assert stats["all_reduces"] == 2
        assert stats["overlap_span"] > 0

    def test_reduction_stats_shapes(self, rng):
        b = make_solver("reduce_overlap: true reduce_buckets: 3",
                        mesh=MeshPlan.data_parallel())
        stats = b.reduction_stats()
        assert stats["collectives_per_step"] == len(stats["bucket_bytes"])
        assert sum(stats["bucket_bytes"]) == sum(
            int(np.prod(np.shape(a)) * 4)
            for lp in b.params.values() for a in lp.values())
        assert make_solver().reduction_stats() is None

    def test_tpu_overlap_flags_env_application(self):
        env = {}
        assert reduction.apply_tpu_overlap_flags(env)
        assert "latency_hiding_scheduler" in env["LIBTPU_INIT_ARGS"]
        assert not reduction.apply_tpu_overlap_flags(env)  # idempotent
        env2 = {"CAFFE_TPU_NO_OVERLAP_FLAGS": "1"}
        assert not reduction.apply_tpu_overlap_flags(env2)
        assert "LIBTPU_INIT_ARGS" not in env2

    def test_tpu_overlap_flags_respect_explicit_operator_value(self):
        # an operator's explicit `=false` opt-out must not be
        # contradicted with a second `=true` copy of the same flag
        env = {"LIBTPU_INIT_ARGS":
               "--xla_tpu_enable_latency_hiding_scheduler=false"}
        reduction.apply_tpu_overlap_flags(env)
        args = env["LIBTPU_INIT_ARGS"]
        assert args.count("latency_hiding_scheduler") == 1
        assert "latency_hiding_scheduler=true" not in args
        # flags the operator did NOT spell are still appended
        assert "async_collective_fusion=true" in args


# ---------------------------------------------------------------------------
# CLI plumbing
# ---------------------------------------------------------------------------

class TestCLIPlumbing:
    def test_cli_byte_budget_overrides_prototxt_bucket_count(
            self, tmp_path, caplog, monkeypatch):
        """A recipe pinning `reduce_buckets` must be switchable to
        byte-budget sizing from the CLI without editing the prototxt —
        the CLI sizing mode clears the prototxt's OTHER mode instead of
        tripping the solver's "not both" validation."""
        from caffe_mpi_tpu.tools.cli import main
        monkeypatch.setenv("CAFFE_TPU_NO_OVERLAP_FLAGS", "1")
        net = tmp_path / "net.prototxt"
        net.write_text(MLP_NET)
        sf = tmp_path / "solver.prototxt"
        sf.write_text(
            f'net: "{net}"\nbase_lr: 0.05 momentum: 0.9\n'
            f'lr_policy: "fixed" max_iter: 2 random_seed: 5\n'
            f'snapshot_prefix: "{tmp_path}/snap"\n'
            f'reduce_overlap: true\nreduce_buckets: 4\n')
        with caplog.at_level(logging.INFO, "caffe_mpi_tpu.solver"):
            assert main(["train", "-solver", str(sf), "-synthetic",
                         "-gpu", "all", "-grad_bucket_mb", "0.001"]) == 0
        # byte-budget mode engaged: > 4 buckets proves the 0.001 MiB
        # budget sized them, not the prototxt count it overrode
        msgs = [r.message for r in caplog.records
                if "overlapped bucketed reduction" in r.message]
        assert msgs, caplog.records
