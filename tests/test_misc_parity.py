"""Tests for propagate_down, python-layer backward, solver train/test_state,
Message.to_node serialization, and the upgrade tool."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.net import Net
from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from caffe_mpi_tpu.solver import Solver


class TestPropagateDown:
    def test_blocks_gradient_per_bottom(self, rng):
        text = """
        layer { name: "in" type: "Input" top: "a" top: "b" top: "t"
                input_param { shape { dim: 2 dim: 3 } shape { dim: 2 dim: 3 }
                              shape { dim: 2 dim: 3 } } }
        layer { name: "e" type: "Eltwise" bottom: "a" bottom: "b" top: "y"
                propagate_down: true propagate_down: false }
        layer { name: "loss" type: "EuclideanLoss" bottom: "y" bottom: "t" top: "l"
                propagate_down: true propagate_down: false }
        """
        net = Net(NetParameter.from_text(text))
        params, state = net.init(jax.random.PRNGKey(0))
        feeds = {"a": jnp.asarray(rng.randn(2, 3).astype(np.float32)),
                 "b": jnp.asarray(rng.randn(2, 3).astype(np.float32)),
                 "t": jnp.asarray(rng.randn(2, 3).astype(np.float32))}
        grads = jax.grad(
            lambda f: net.apply(params, state, f, train=True)[2])(feeds)
        assert float(jnp.sum(jnp.abs(grads["a"]))) > 0
        assert float(jnp.sum(jnp.abs(grads["b"]))) == 0.0  # blocked


class ScaledLayer:
    """Python layer with a custom backward (x3 forward, x3 grads)."""

    def infer_shapes(self, bottom_shapes):
        return [bottom_shapes[0]]

    def forward(self, bottoms):
        return [3.0 * bottoms[0]]

    def backward(self, top_diffs, bottoms):
        return [3.0 * top_diffs[0]]


class TestPythonLayerBackward:
    def test_custom_vjp(self, rng):
        net = Net(NetParameter.from_text("""
        layer { name: "in" type: "Input" top: "x"
                input_param { shape { dim: 2 dim: 4 } } }
        layer { name: "py" type: "Python" bottom: "x" top: "y"
                python_param { module: "test_misc_parity" layer: "ScaledLayer" } }
        """))
        params, state = net.init(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(2, 4).astype(np.float32))

        def loss(x):
            blobs, _, _ = net.apply(params, state, {"x": x}, train=True)
            return jnp.sum(blobs["y"] ** 2)

        g = jax.grad(loss)(x)
        # d/dx sum((3x)^2) = 18x
        np.testing.assert_allclose(np.array(g), 18 * np.array(x), rtol=1e-5)


class TestSolverStates:
    def test_train_state_stage_selects_layers(self):
        sp = SolverParameter.from_text("""
        base_lr: 0.1 lr_policy: "fixed" max_iter: 1 type: "SGD"
        train_state { stage: "with_aux" }
        """)
        sp.net_param = NetParameter.from_text("""
        layer { name: "in" type: "Input" top: "x" top: "t"
                input_param { shape { dim: 2 dim: 4 } shape { dim: 2 } } }
        layer { name: "ip" type: "InnerProduct" bottom: "x" top: "y"
                inner_product_param { num_output: 3
                  weight_filler { type: "xavier" } } }
        layer { name: "aux" type: "InnerProduct" bottom: "x" top: "aux"
                include { stage: "with_aux" }
                inner_product_param { num_output: 3
                  weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t"
                top: "l" }
        layer { name: "aux_loss" type: "SoftmaxWithLoss" bottom: "aux"
                bottom: "t" top: "al" include { stage: "with_aux" } }
        """)
        solver = Solver(sp)
        assert "aux" in [l.name for l in solver.net.layers]
        sp2 = SolverParameter.from_text(
            'base_lr: 0.1 lr_policy: "fixed" max_iter: 1 type: "SGD"')
        sp2.net_param = sp.net_param
        solver2 = Solver(sp2)
        assert "aux" not in [l.name for l in solver2.net.layers]


class TestToNode:
    def test_roundtrip_real_model(self):
        net = NetParameter.from_file("models/alexnet/train_val.prototxt")
        text = net.to_prototxt()
        again = NetParameter.from_text(text)
        assert len(again.layer) == len(net.layer)
        assert again.layer[1].convolution_param.num_output == \
            net.layer[1].convolution_param.num_output
        # enum fields unquoted
        assert "pool: MAX" in text
