"""Data-parallel tests on the virtual 8-device CPU mesh.

The key invariant (reference test_gradient_based_solver.cpp:484-485 uses
constant data so device count doesn't change results): training on an
8-device mesh must produce the SAME parameters as single-device training on
the same global batch — the DP allreduce is then provably a mean, not a
topology-dependent approximation.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.parallel import MeshPlan
from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from caffe_mpi_tpu.solver import Solver

NET = """
name: "dp_mlp"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 16 dim: 8 } shape { dim: 16 } } }
layer { name: "ip1" type: "InnerProduct" bottom: "x" top: "h"
        inner_product_param { num_output: 32 weight_filler { type: "xavier" } } }
layer { name: "r" type: "ReLU" bottom: "h" top: "h" }
layer { name: "ip2" type: "InnerProduct" bottom: "h" top: "y"
        inner_product_param { num_output: 4 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t" top: "l" }
"""


def make_solver(mesh=None):
    sp = SolverParameter.from_text(
        'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" max_iter: 20 '
        'type: "SGD" random_seed: 7'
    )
    sp.net_param = NetParameter.from_text(NET)
    return Solver(sp, mesh=mesh)


def batches(n, seed=3):
    r = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        out.append({
            "x": jnp.asarray(r.randn(16, 8).astype(np.float32)),
            "t": jnp.asarray(r.randint(0, 4, 16)),
        })
    return out


class TestMeshPlan:
    def test_data_parallel_mesh(self):
        plan = MeshPlan.data_parallel()
        assert plan.n_data == 8
        assert plan.mesh.axis_names == ("data", "model")

    def test_shard_feeds(self):
        plan = MeshPlan.data_parallel()
        feeds = {"x": jnp.ones((16, 4))}
        sharded = plan.shard_feeds(feeds)
        shards = sharded["x"].addressable_shards
        assert len(shards) == 8
        assert shards[0].data.shape == (2, 4)

    def test_from_shape_validates(self):
        with pytest.raises(ValueError, match="devices"):
            MeshPlan.from_shape(data=3, model=2)


class TestDataParallelTraining:
    def test_mesh_matches_single_device(self):
        data = batches(20)
        s_single = make_solver(mesh=None)
        s_mesh = make_solver(mesh=MeshPlan.data_parallel())
        l1 = s_single.step(10, lambda it: data[it])
        l2 = s_mesh.step(10, lambda it: data[it])
        assert l1 == pytest.approx(l2, rel=1e-4)
        w1 = np.array(s_single.params["ip1"]["weight"])
        w2 = np.array(s_mesh.params["ip1"]["weight"])
        np.testing.assert_allclose(w1, w2, rtol=2e-4, atol=1e-6)

    def test_params_stay_replicated(self):
        s = make_solver(mesh=MeshPlan.data_parallel())
        data = batches(4)
        s.step(2, lambda it: data[it % 4])
        w = s.params["ip1"]["weight"]
        assert w.sharding.is_fully_replicated
        # every device holds identical weights (reference broadcast invariant)
        shard_vals = [np.asarray(sh.data) for sh in w.addressable_shards]
        for v in shard_vals[1:]:
            np.testing.assert_array_equal(shard_vals[0], v)

    def test_mesh_with_iter_size(self):
        """iter_size accumulation under SPMD sharding must equal the
        single-device result too."""
        data = batches(8)
        stacked = [{k: jnp.concatenate([data[2 * i][k], data[2 * i + 1][k]])
                    for k in data[0]} for i in range(4)]

        def ms(mesh, iter_size):
            sp = SolverParameter.from_text(
                f'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" '
                f'max_iter: 8 type: "SGD" random_seed: 7 iter_size: {iter_size}')
            sp.net_param = NetParameter.from_text(NET)
            return Solver(sp, mesh=mesh)

        s_mesh = ms(MeshPlan.data_parallel(), 2)
        s_one = ms(None, 2)
        s_mesh.step(4, lambda it: data[it])
        s_one.step(4, lambda it: data[it])
        np.testing.assert_allclose(np.array(s_mesh.params["ip1"]["weight"]),
                                   np.array(s_one.params["ip1"]["weight"]),
                                   rtol=2e-4, atol=1e-6)

    def test_tensor_parallel_matches_replicated(self):
        """2x4 mesh (dp x tp): ip1's weight sharded over 'model' must train
        to the same parameters as plain replicated DP — GSPMD inserts the
        Megatron-style collectives without changing the math."""
        data = batches(12)

        def ms(shardings):
            sp = SolverParameter.from_text(
                'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" '
                'max_iter: 6 type: "SGD" random_seed: 7')
            sp.net_param = NetParameter.from_text(NET)
            mesh = MeshPlan.from_shape(data=2, model=4)
            return Solver(sp, mesh=mesh, param_shardings=shardings)

        s_tp = ms({"ip1": ("model", None)})
        s_rep = ms(None)
        w = s_tp.params["ip1"]["weight"]
        assert not w.sharding.is_fully_replicated  # actually sharded
        s_tp.step(6, lambda it: data[it % 12])
        s_rep.step(6, lambda it: data[it % 12])
        np.testing.assert_allclose(np.array(s_tp.params["ip1"]["weight"]),
                                   np.array(s_rep.params["ip1"]["weight"]),
                                   rtol=2e-4, atol=1e-6)
        # sharding preserved through donated updates
        assert not s_tp.params["ip1"]["weight"].sharding.is_fully_replicated

    CONV_NET = """
    name: "tp_conv"
    layer { name: "in" type: "Input" top: "x" top: "t"
            input_param { shape { dim: 8 dim: 3 dim: 10 dim: 10 }
                          shape { dim: 8 } } }
    layer { name: "conv1" type: "Convolution" bottom: "x" top: "c1"
            convolution_param { num_output: 16 kernel_size: 3 pad: 1
              weight_filler { type: "msra" } } }
    layer { name: "r1" type: "ReLU" bottom: "c1" top: "c1" }
    layer { name: "conv2" type: "Convolution" bottom: "c1" top: "c2"
            convolution_param { num_output: 8 kernel_size: 3 pad: 1
              weight_filler { type: "msra" } } }
    layer { name: "pool" type: "Pooling" bottom: "c2" top: "p"
            pooling_param { pool: AVE global_pooling: true } }
    layer { name: "ip" type: "InnerProduct" bottom: "p" top: "y"
            inner_product_param { num_output: 4
              weight_filler { type: "xavier" } } }
    layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t"
            top: "l" }
    """

    def test_conv_tp_matches_replicated(self):
        """Convolution output channels sharded over 'model' (weight
        (Cout,Cin,kh,kw) dim 0 + the per-channel bias) trains identically
        to fully-replicated DP on the same 2x4 mesh — GSPMD partitions the
        conv; the rules are not dense-layer-only (mesh.py claims
        generality; this is the proof on conv)."""
        r = np.random.RandomState(5)
        data = [{"x": jnp.asarray(r.randn(8, 3, 10, 10).astype(np.float32)),
                 "t": jnp.asarray(r.randint(0, 4, 8))} for _ in range(6)]

        def ms(shardings):
            sp = SolverParameter.from_text(
                'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" '
                'max_iter: 8 type: "SGD" random_seed: 7')
            sp.net_param = NetParameter.from_text(self.CONV_NET)
            mesh = MeshPlan.from_shape(data=2, model=4)
            return Solver(sp, mesh=mesh, param_shardings=shardings)

        s_tp = ms({"conv1": ("model",), "ip": ("model", None)})
        s_rep = ms(None)
        w = s_tp.params["conv1"]["weight"]
        assert not w.sharding.is_fully_replicated
        b = s_tp.params["conv1"]["bias"]
        assert not b.sharding.is_fully_replicated  # bias rides along
        s_tp.step(6, lambda it: data[it % 6])
        s_rep.step(6, lambda it: data[it % 6])
        for lname in ("conv1", "conv2", "ip"):
            np.testing.assert_allclose(
                np.array(s_tp.params[lname]["weight"]),
                np.array(s_rep.params[lname]["weight"]),
                rtol=2e-4, atol=1e-6)
        assert not s_tp.params["conv1"]["weight"].sharding.is_fully_replicated

    def test_tp_sharding_survives_restore(self, tmp_path):
        data = batches(4)
        sp = SolverParameter.from_text(
            'base_lr: 0.05 lr_policy: "fixed" max_iter: 4 type: "SGD" '
            'random_seed: 7')
        sp.snapshot_prefix = str(tmp_path / "tp")
        sp.net_param = NetParameter.from_text(NET)
        mesh = MeshPlan.from_shape(data=2, model=4)
        s = Solver(sp, mesh=mesh, param_shardings={"ip1": ("model", None)})
        s.step(2, lambda it: data[it % 4])
        path = s.snapshot()
        s.restore(path)
        assert not s.params["ip1"]["weight"].sharding.is_fully_replicated
        assert not s.opt_state["ip1"]["weight"][0].sharding.is_fully_replicated
        s.step(1, lambda it: data[it % 4])  # still trains after restore

    def test_native_sharded_checkpoint_roundtrip(self, tmp_path):
        """snapshot_native writes per-shard (orbax/tensorstore, no host
        gather) and restore preserves values, iter, optimizer slots, and
        the TP sharding — the at-scale path the gather-based
        .caffemodel/.solverstate interop snapshot can't serve."""
        data = batches(4)

        def ms():
            sp = SolverParameter.from_text(
                'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" '
                'max_iter: 20 type: "Adam" random_seed: 7')
            sp.snapshot_prefix = str(tmp_path / "nat")
            sp.net_param = NetParameter.from_text(NET)
            mesh = MeshPlan.from_shape(data=2, model=4)
            return Solver(sp, mesh=mesh,
                          param_shardings={"ip1": ("model", None)})

        s = ms()
        s.step(3, lambda it: data[it % 4])
        path = s.snapshot_native()
        w0 = np.array(s.params["ip1"]["weight"])
        m0 = np.array(s.opt_state["ip1"]["weight"][0])
        s.step(2, lambda it: data[it % 4])
        assert not np.allclose(np.array(s.params["ip1"]["weight"]), w0)

        s2 = ms()
        s2.restore(path)  # dispatches on the .orbax suffix
        assert s2.iter == 3
        np.testing.assert_array_equal(np.array(s2.params["ip1"]["weight"]), w0)
        np.testing.assert_array_equal(
            np.array(s2.opt_state["ip1"]["weight"][0]), m0)
        assert not s2.params["ip1"]["weight"].sharding.is_fully_replicated
        s2.step(1, lambda it: data[it % 4])  # still trains

    def test_tp_misuse_raises(self):
        sp = SolverParameter.from_text(
            'base_lr: 0.05 lr_policy: "fixed" max_iter: 1 type: "SGD"')
        sp.net_param = NetParameter.from_text(NET)
        with pytest.raises(ValueError, match="requires a mesh"):
            Solver(sp, param_shardings={"ip1": ("model", None)})
        with pytest.raises(ValueError, match="unknown layers"):
            Solver(sp, mesh=MeshPlan.data_parallel(),
                   param_shardings={"nope": ("model", None)})

    def test_grad_transform_hook(self):
        """Custom allreduce hook (the P2PSync::allreduce analogue)."""
        calls = []

        def hook(grads):
            calls.append(1)
            return jax.tree.map(lambda g: g * 1.0, grads)

        sp = SolverParameter.from_text(
            'base_lr: 0.05 lr_policy: "fixed" max_iter: 5 type: "SGD"')
        sp.net_param = NetParameter.from_text(NET)
        s = Solver(sp, grad_transform=hook)
        data = batches(2)
        s.step(2, lambda it: data[it % 2])
        assert calls  # hook traced into the step
