"""Serving plane (ISSUE 7): bucket-ladder planner, batching-window
semantics, LRU spill/reload, the zero-recompile proof, and score
equality with the classic pad-to-declared-batch Classifier loop.

Reference: python/caffe/classifier.py's static-batch forward is the
behavior baseline; the serving engine must reproduce its scores exactly
while batching/padding/residency happen around it.
"""

import os
import threading
import time

import numpy as np
import pytest

import caffe_mpi_tpu.pycaffe as caffe
from caffe_mpi_tpu.serving import (ServingEngine, bucket_for, plan_ladder)
from caffe_mpi_tpu.serving.engine import BucketedForward

TOY_NET = """
name: "toy"
layer {{ name: "data" type: "Input" top: "data"
        input_param {{ shape {{ dim: {batch} dim: 3 dim: 8 dim: 8 }} }} }}
layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "score"
        inner_product_param {{ num_output: 5
          weight_filler {{ type: "xavier" }} }} }}
layer {{ name: "prob" type: "Softmax" bottom: "score" top: "prob" }}
"""


def write_toy(tmp_path, batch=8, name="deploy.prototxt", seed=0):
    model = tmp_path / name
    model.write_text(TOY_NET.format(batch=batch))
    net = caffe.Net(str(model), caffe.TEST)
    weights = str(tmp_path / (name + ".caffemodel"))
    net.save(weights)
    return str(model), weights


def imgs(n, seed=0, hw=(8, 8)):
    r = np.random.RandomState(seed)
    return [r.rand(*hw, 3).astype(np.float32) for _ in range(n)]


# ---------------------------------------------------------------------------
# bucket-ladder planner

class TestLadderPlanner:
    def test_default_geometric(self):
        assert plan_ladder(64) == (1, 4, 16, 64)
        assert plan_ladder(16) == (1, 4, 16)
        assert plan_ladder(10) == (1, 4, 10)

    def test_max_one(self):
        assert plan_ladder(1) == (1,)

    def test_explicit_spec_string_and_iterable(self):
        assert plan_ladder(6, "1,2,4") == (1, 2, 4, 6)
        assert plan_ladder(6, [4, 2, 1]) == (1, 2, 4, 6)

    def test_spec_dedup_and_clip_above_max(self):
        assert plan_ladder(4, "1,1,8") == (1, 4)
        assert plan_ladder(4, [2, 2, 4]) == (2, 4)

    def test_spec_always_includes_max(self):
        assert plan_ladder(9, "2")[-1] == 9

    def test_invalid_specs_raise(self):
        with pytest.raises(ValueError):
            plan_ladder(0)
        with pytest.raises(ValueError):
            plan_ladder(8, "0,4")
        with pytest.raises(ValueError):
            plan_ladder(8, "-1")
        with pytest.raises(ValueError):
            plan_ladder(8, "a,b")
        with pytest.raises(ValueError):
            plan_ladder(8, "")
        with pytest.raises(ValueError):
            plan_ladder(8, [])

    def test_bucket_for(self):
        ladder = (1, 4, 16)
        assert bucket_for(1, ladder) == 1
        assert bucket_for(2, ladder) == 4
        assert bucket_for(4, ladder) == 4
        assert bucket_for(5, ladder) == 16
        assert bucket_for(99, ladder) == 16  # callers chunk at max
        with pytest.raises(ValueError):
            bucket_for(0, ladder)


# ---------------------------------------------------------------------------
# engine basics + zero-recompile

class TestZeroRecompile:
    def test_warm_equals_ladder_and_steady_state_never_compiles(
            self, tmp_path):
        model, weights = write_toy(tmp_path, batch=8)
        with ServingEngine(window_ms=5) as eng:
            eng.load_model("a", model, weights)
            eng.load_model("b", model, weights)
            # every ladder bucket compiled at load, nothing else
            assert eng.warmed_buckets == 2 * len(plan_ladder(8))
            assert eng.compile_count == eng.warmed_buckets
            at_warm = eng.compile_count
            # mixed-size arrival trace across both resident models
            r = np.random.RandomState(1)
            for _ in range(12):
                name = "a" if r.rand() < 0.5 else "b"
                n = int(r.randint(1, 9))
                scores = eng.classify(name, imgs(n, seed=n))
                assert scores.shape == (n, 5)
                np.testing.assert_allclose(scores.sum(1), 1.0, atol=1e-5)
            eng.drain()
            assert eng.compile_count == at_warm  # ZERO post-warmup compiles
            st = eng.stats()
            assert st["compile_count"] == st["warmed_buckets"]
            assert st["requests"] > 0 and st["p99_ms"] >= st["p50_ms"] > 0
            assert st["img_per_s"] > 0

    def test_reload_same_name_keeps_invariant(self, tmp_path):
        # replacing a model via load_model(same name) retires the old
        # model's warmed buckets; the old compiles stay in the counter,
        # so the invariant must count them on the warmed side too
        model, weights = write_toy(tmp_path, batch=4)
        with ServingEngine() as eng:
            eng.load_model("a", model, weights)
            eng.load_model("a", model, weights)  # e.g. updated weights
            assert eng.compile_count == eng.warmed_buckets
            scores = eng.classify("a", imgs(3))
            assert scores.shape == (3, 5)
            assert eng.compile_count == eng.warmed_buckets

    def test_reload_during_open_window_dispatches_current_model(
            self, tmp_path):
        # a request waiting in an open batching window when load_model
        # replaces its model must be scored by the CURRENT model's
        # weights, not the retired object captured at window-open
        m1, w1 = write_toy(tmp_path, batch=4, name="a.prototxt")
        net = caffe.Net(m1, caffe.TEST)
        net.copy_from(w1)
        net.params["ip"][0].data = net.params["ip"][0].data * 3.0
        w2 = str(tmp_path / "scaled.caffemodel")  # distinct weights
        net.save(w2)
        with ServingEngine(window_ms=60_000) as eng:
            eng.load_model("m", m1, w1)
            data = [im for im in imgs(4, seed=9)]
            first = eng.submit("m", data[0])     # opens a 60s window
            eng.load_model("m", m1, w2)          # reload mid-window
            rest = [eng.submit("m", im) for im in data[1:]]
            rows = np.stack([f.result(timeout=30)
                             for f in [first] + rest])  # full bucket
            want = eng.classify("m", data)       # current (w2) scores
            np.testing.assert_allclose(rows, want, rtol=1e-6, atol=1e-7)
            assert eng.compile_count == eng.warmed_buckets

    def test_done_callback_reading_stats_does_not_deadlock(self, tmp_path):
        # set_result runs done-callbacks synchronously in the harvest
        # thread; a callback reading stats()/records() must not
        # re-enter a lock the harvester is still holding
        model, weights = write_toy(tmp_path)
        with ServingEngine(window_ms=0) as eng:
            eng.load_model("a", model, weights)
            seen = []
            fut = eng.submit("a", imgs(1)[0])
            fut.add_done_callback(
                lambda f: seen.append(eng.stats()["requests"]))
            fut.result(timeout=30)
            eng.drain(timeout=30)  # hangs if the harvester deadlocked
            assert seen and seen[0] >= 1

    def test_unknown_model_raises(self, tmp_path):
        model, weights = write_toy(tmp_path)
        with ServingEngine() as eng:
            eng.load_model("a", model, weights)
            with pytest.raises(KeyError):
                eng.submit("nope", imgs(1)[0])

    def test_wrong_shape_request_rejected_at_submit(self, tmp_path):
        # a malformed row must fail in the CALLER's thread — inside a
        # batch it would poison every co-batched request's future
        model, weights = write_toy(tmp_path)
        with ServingEngine() as eng:
            eng.load_model("a", model, weights)
            with pytest.raises(ValueError, match="row shape"):
                eng.submit("a", np.zeros((5, 5, 5), np.float32),
                           preprocess=False)
            assert eng.classify("a", imgs(2)).shape == (2, 5)

    def test_explicit_ladder_knob(self, tmp_path):
        model, weights = write_toy(tmp_path, batch=8)
        with ServingEngine(buckets="2,8") as eng:
            m = eng.load_model("a", model, weights)
            assert m.fwd.ladder == (2, 8)
            assert eng.compile_count == 2

    def test_negative_knobs_rejected_at_init(self):
        with pytest.raises(ValueError, match="serve_window_ms"):
            ServingEngine(window_ms=-1, start=False)
        with pytest.raises(ValueError, match="serve_hbm_mb"):
            ServingEngine(hbm_mb=-2, start=False)


# ---------------------------------------------------------------------------
# batching-window semantics

class TestBatchingWindow:
    def _engine(self, tmp_path, window_ms):
        model, weights = write_toy(tmp_path, batch=4)
        eng = ServingEngine(window_ms=window_ms)
        eng.load_model("m", model, weights)
        return eng

    def test_full_max_bucket_closes_window_early(self, tmp_path):
        # a 10s window must NOT make a full bucket wait 10s
        eng = self._engine(tmp_path, window_ms=10_000)
        t0 = time.perf_counter()
        futs = [eng._batcher.submit("m", np.zeros((3, 8, 8), np.float32))
                for _ in range(4)]
        for f in futs:
            f.result(timeout=30)
        assert time.perf_counter() - t0 < 5.0
        assert list(eng._batcher.dispatches) == [("m", 4, 4)]
        eng.close()

    def test_window_expiry_batches_partial_group(self, tmp_path):
        eng = self._engine(tmp_path, window_ms=150)
        futs = [eng._batcher.submit("m", np.zeros((3, 8, 8), np.float32))
                for _ in range(3)]
        for f in futs:
            f.result(timeout=30)
        # all three arrived inside one window: ONE dispatch, padded 3->4
        assert list(eng._batcher.dispatches) == [("m", 3, 4)]
        eng.close()

    def test_zero_window_dispatches_immediately(self, tmp_path):
        eng = self._engine(tmp_path, window_ms=0)
        for _ in range(3):
            eng._batcher.submit(
                "m", np.zeros((3, 8, 8), np.float32)).result(timeout=30)
        # sequential submit+wait: three solo dispatches on bucket 1
        assert list(eng._batcher.dispatches) == [("m", 1, 1)] * 3
        eng.close()

    def test_close_cancels_pending_and_unblocks_drain(self, tmp_path):
        # requests queued inside a long window when close() runs can
        # never complete — they must come back CANCELLED, and drain()
        # must not hang on their never-retired count
        eng = self._engine(tmp_path, window_ms=60_000)
        futs = [eng._batcher.submit("m", np.zeros((3, 8, 8), np.float32))
                for _ in range(2)]
        eng.close()
        assert all(f.cancelled() for f in futs)
        eng._batcher.drain(timeout=1.0)  # would TimeoutError pre-fix

    def test_burst_larger_than_max_bucket_chunks(self, tmp_path):
        eng = self._engine(tmp_path, window_ms=100)
        scores = eng.classify("m", [im[:, :, :] for im in imgs(9, seed=3)])
        assert scores.shape == (9, 5)
        eng.drain()
        total = sum(n for (_, n, _) in eng._batcher.dispatches)
        assert total == 9
        # no dispatch exceeds the max bucket
        assert all(b <= 4 for (_, _, b) in eng._batcher.dispatches)
        eng.close()

    def test_interleaved_models_group_per_model(self, tmp_path):
        model, weights = write_toy(tmp_path, batch=4)
        with ServingEngine(window_ms=200) as eng:
            eng.load_model("a", model, weights)
            eng.load_model("b", model, weights)
            futs = []
            for name in ("a", "b", "a", "b", "a"):
                futs.append(eng._batcher.submit(
                    name, np.zeros((3, 8, 8), np.float32)))
            for f in futs:
                f.result(timeout=30)
            # per-model grouping: one batch of 3 a's, one of 2 b's
            got = sorted(eng._batcher.dispatches)
            assert got == [("a", 3, 4), ("b", 2, 4)]


# ---------------------------------------------------------------------------
# LRU spill / reload

class TestLRUResidency:
    def test_spill_reload_round_trip(self, tmp_path):
        model, weights = write_toy(tmp_path, batch=4)
        with ServingEngine(window_ms=0) as eng:
            a = eng.load_model("a", model, weights)
            bytes_one = a.param_bytes / 2**20
            # budget fits exactly one model
            eng.hbm_budget = int(bytes_one * 1.5 * 2**20)
            b = eng.load_model("b", model, weights)
            assert b.resident and not a.resident  # a was LRU -> spilled
            assert eng.spills == 1

            ref = eng.classify("b", imgs(2, seed=7))
            # serving the spilled model reloads it and evicts b
            out_a = eng.classify("a", imgs(2, seed=7))
            assert a.resident and not b.resident
            assert eng.spills == 2 and eng.reloads >= 1
            # round-trip: b comes back and scores are unchanged
            out_b = eng.classify("b", imgs(2, seed=7))
            assert b.resident and not a.resident
            np.testing.assert_array_equal(ref, out_b)
            # same prototxt + same weights file: a == b scores too
            np.testing.assert_array_equal(out_a, out_b)
            # residency churn never compiled anything new
            assert eng.compile_count == eng.warmed_buckets

    def test_oversized_model_stays_resident_with_unlimited_default(
            self, tmp_path):
        model, weights = write_toy(tmp_path, batch=4)
        with ServingEngine() as eng:  # serve_hbm_mb 0 = unlimited
            a = eng.load_model("a", model, weights)
            b = eng.load_model("b", model, weights)
            assert a.resident and b.resident and eng.spills == 0


# ---------------------------------------------------------------------------
# engine vs classic Classifier scores

class TestClassifierEquality:
    def _classic_forward(self, net, crops):
        """The pre-ISSUE-7 Classifier loop: preprocess, pad every chunk
        to the net's declared batch, forward, strip padding."""
        in_ = net.inputs[0]
        batch_size = net._net.blob_shapes[in_][0]
        out_blob = net.outputs[-1]
        preds = []
        for start in range(0, len(crops), batch_size):
            chunk = crops[start:start + batch_size]
            data = np.stack([net.transformer.preprocess(in_, c)
                             for c in chunk])
            if len(data) < batch_size:
                pad = np.zeros((batch_size - len(data), *data.shape[1:]),
                               np.float32)
                data = np.concatenate([data, pad])
            out = net.forward(**{in_: data})
            preds.append(out[out_blob][:len(chunk)])
        return np.concatenate(preds)

    def test_non_input_deploy_net_falls_back_to_classic_loop(
            self, tmp_path):
        # MemoryData-fed deploy nets have no rewritable Input batch dim
        # — Classifier must keep the old declared-batch loop for them
        net_txt = """
name: "memtoy"
layer { name: "data" type: "MemoryData" top: "data" top: "label"
        memory_data_param { batch_size: 4 channels: 3
                            height: 8 width: 8 } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "score"
        inner_product_param { num_output: 5
          weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "score" top: "prob" }
"""
        model = tmp_path / "mem.prototxt"
        model.write_text(net_txt)
        net = caffe.Net(str(model), caffe.TEST)
        weights = str(tmp_path / "mem.caffemodel")
        net.save(weights)
        clf = caffe.Classifier(str(model), weights, image_dims=(8, 8))
        preds = clf.predict(imgs(3, seed=2), oversample=False)
        assert preds.shape == (3, 5)
        np.testing.assert_allclose(preds.sum(1), 1.0, atol=1e-5)
        assert clf._bucket_fwd is False  # classic loop engaged

    def test_multi_input_deploy_net_falls_back_to_classic_loop(
            self, tmp_path):
        # two-Input deploy nets pass BucketedForward's constructor but
        # fail its one-input check at forward time; Classifier must
        # fall back (pycaffe zero-fills the unfed second input)
        net_txt = """
name: "twotoy"
layer { name: "data" type: "Input" top: "data"
        input_param { shape { dim: 4 dim: 3 dim: 8 dim: 8 } } }
layer { name: "aux" type: "Input" top: "aux"
        input_param { shape { dim: 4 dim: 2 } } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "score"
        inner_product_param { num_output: 5
          weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "score" top: "prob" }
"""
        model = tmp_path / "two.prototxt"
        model.write_text(net_txt)
        net = caffe.Net(str(model), caffe.TEST)
        weights = str(tmp_path / "two.caffemodel")
        net.save(weights)
        clf = caffe.Classifier(str(model), weights, image_dims=(8, 8))
        preds = clf.predict(imgs(2, seed=4), oversample=False)
        assert preds.shape == (2, 5)
        np.testing.assert_allclose(preds.sum(1), 1.0, atol=1e-5)
        assert clf._bucket_fwd is False  # classic loop engaged

    def test_empty_crop_list_raises_cleanly(self, tmp_path):
        model, weights = write_toy(tmp_path, batch=4)
        clf = caffe.Classifier(model, weights, image_dims=(8, 8))
        with pytest.raises(ValueError, match="empty input"):
            clf._forward_batched([])

    def test_predict_populates_net_blobs(self, tmp_path):
        # pycaffe parity: after predict(), net.blobs exposes every blob
        # of the last executed batch (the standard feature-extraction
        # pattern) — the bucketed path must keep the contract
        model, weights = write_toy(tmp_path, batch=4)
        clf = caffe.Classifier(model, weights, image_dims=(8, 8))
        preds = clf.predict(imgs(2, seed=5), oversample=False)
        prob = clf.blobs["prob"].data
        np.testing.assert_allclose(prob[:2], preds, rtol=1e-6, atol=1e-7)
        assert clf.blobs["score"].data.shape[1] == 5  # intermediates too

    @pytest.mark.parametrize("n", [1, 2, 4, 7])
    def test_classifier_matches_classic_loop(self, tmp_path, n):
        model, weights = write_toy(tmp_path, batch=4)
        clf = caffe.Classifier(model, weights, image_dims=(8, 8))
        crops = imgs(n, seed=n)
        classic = self._classic_forward(clf, list(crops))
        bucketed = clf._forward_batched(list(crops))
        np.testing.assert_allclose(bucketed, classic, rtol=1e-6, atol=1e-7)

    def test_predict_oversample_shapes_and_rows(self, tmp_path):
        model, weights = write_toy(tmp_path, batch=4)
        clf = caffe.Classifier(model, weights, image_dims=(10, 10))
        preds = clf.predict(imgs(2, seed=5, hw=(12, 12)), oversample=True)
        assert preds.shape == (2, 5)
        np.testing.assert_allclose(preds.sum(1), 1.0, atol=1e-5)

    def test_engine_matches_classifier(self, tmp_path):
        model, weights = write_toy(tmp_path, batch=4)
        clf = caffe.Classifier(model, weights)
        with ServingEngine(window_ms=50) as eng:
            eng.load_model("m", model, weights)
            ims = imgs(5, seed=9)
            want = clf.predict(ims, oversample=False)
            got = eng.classify("m", ims)
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)

    def test_detector_still_detects(self, tmp_path):
        model, weights = write_toy(tmp_path, batch=4)
        from PIL import Image
        img = Image.fromarray(np.random.RandomState(0).randint(
            0, 255, (16, 16, 3), np.uint8))
        fname = str(tmp_path / "im.png")
        img.save(fname)
        det = caffe.Detector(model, weights)
        out = det.detect_windows([(fname, [(0, 0, 12, 12), (2, 2, 15, 15),
                                           (1, 0, 9, 14)])])
        assert len(out) == 3
        for o in out:
            assert o["prediction"].shape == (5,)


# ---------------------------------------------------------------------------
# BucketedForward surface details

class TestBucketedForward:
    def test_multi_input_net_rejected(self, tmp_path):
        from caffe_mpi_tpu.proto import NetParameter
        two = NetParameter.from_text("""
        layer { name: "d" type: "Input" top: "x" top: "y"
                input_param { shape { dim: 2 dim: 3 }
                              shape { dim: 2 dim: 3 } } }
        layer { name: "add" type: "Eltwise" bottom: "x" bottom: "y"
                top: "s" }
        """)
        fwd = BucketedForward(two)
        with pytest.raises(ValueError, match="one input blob"):
            fwd.init()

    def test_no_input_layer_rejected(self):
        from caffe_mpi_tpu.proto import NetParameter
        with pytest.raises(ValueError, match="deploy prototxt"):
            BucketedForward(NetParameter.from_text("""
            layer { name: "d" type: "DummyData" top: "x"
                    dummy_data_param { shape { dim: 2 dim: 3 } } }
            """))

    def test_cold_bucket_compile_is_counted(self, tmp_path):
        from caffe_mpi_tpu.proto import NetParameter
        param = NetParameter.from_text(TOY_NET.format(batch=8))
        fwd = BucketedForward(param, ladder=(2, 8))
        params, state = fwd.init()
        # no warm(): the first forward compiles on demand — and counts
        assert fwd.counter.count == 0
        out = fwd.forward(params, state, np.zeros((2, 3, 8, 8), np.float32))
        assert out.shape == (2, 5)
        assert fwd.counter.count == 1
        # same bucket again: cached, no new compile
        fwd.forward(params, state, np.zeros((1, 3, 8, 8), np.float32))
        assert fwd.counter.count == 1  # 1 -> bucket 2, already built

    def test_smoke_cli(self, tmp_path, capsys):
        """`caffe serve -smoke N` end to end (HTTP + engine legs)."""
        from caffe_mpi_tpu.tools.cli import main as cli_main
        model, weights = write_toy(tmp_path, batch=4)
        rc = cli_main(["serve", "-model", model, "-weights", weights,
                       "-smoke", "8", "-serve_window_ms", "20"])
        assert rc == 0
        out = capsys.readouterr().out
        import json
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        stats = json.loads(line)["serve_smoke"]
        assert stats["post_warmup_compiles"] == 0
        assert stats["compile_count"] == stats["warmed_buckets"]
        assert stats["requests"] >= 8
