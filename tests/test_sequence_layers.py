"""Attention + MoE as prototxt layer types (layers/sequence.py) — the
TPU-native extension surface: gradchecked like every other op, trainable
through the Solver, and expert-shardable via param_shardings."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.net import Net
from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from caffe_mpi_tpu.solver import Solver

from gradcheck import check_gradients
from test_layers import make_layer, rand


class TestAttentionLayer:
    def _layer(self, extra="", shape=(2, 8, 16)):
        return make_layer(
            'name: "attn" type: "Attention" bottom: "x" top: "y"\n'
            f'attention_param {{ num_heads: 4 {extra} }}',
            [shape],
        )

    def test_output_shape_and_params(self, rng):
        layer, params, state = self._layer()
        assert set(params) == {"qkv_weight", "qkv_bias", "proj_weight",
                               "proj_bias"}
        assert params["qkv_weight"].shape == (48, 16)
        x = rand((2, 8, 16), rng)
        (y,), _ = layer.apply(params, state, [x], train=True, rng=None)
        assert y.shape == (2, 8, 16)

    def test_matches_ops_attention(self, rng):
        """The layer is exactly qkv-proj + ops.attention + out-proj."""
        from caffe_mpi_tpu.ops.attention import attention
        layer, params, state = self._layer("causal: true")
        x = rand((2, 8, 16), rng)
        (y,), _ = layer.apply(params, state, [x], train=True, rng=None)
        qkv = np.asarray(x) @ np.asarray(params["qkv_weight"]).T \
            + np.asarray(params["qkv_bias"])
        q, k, v = np.split(qkv, 3, axis=-1)
        shp = (2, 8, 4, 4)
        ref = attention(jnp.asarray(q.reshape(shp)),
                        jnp.asarray(k.reshape(shp)),
                        jnp.asarray(v.reshape(shp)), causal=True)
        ref = np.asarray(ref).reshape(2, 8, 16) \
            @ np.asarray(params["proj_weight"]).T \
            + np.asarray(params["proj_bias"])
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)

    def test_gradients(self, rng):
        layer, params, state = self._layer(shape=(1, 4, 8))
        check_gradients(layer, params, state, [rand((1, 4, 8), rng)])

    def test_causal_gradients(self, rng):
        layer, params, state = self._layer("causal: true", shape=(1, 4, 8))
        check_gradients(layer, params, state, [rand((1, 4, 8), rng)])

    def test_rejects_bad_heads(self):
        with pytest.raises(ValueError, match="divisible"):
            self._layer("num_heads: 5")

    def test_param_block_optional(self, rng):
        """No attention_param block -> single-head defaults, not a crash."""
        layer, params, state = make_layer(
            'name: "a" type: "Attention" bottom: "x" top: "y"', [(1, 4, 8)])
        (y,), _ = layer.apply(params, state, [rand((1, 4, 8), rng)],
                              train=True, rng=None)
        assert y.shape == (1, 4, 8)

    def test_moe_param_required(self):
        with pytest.raises(ValueError, match="num_experts"):
            make_layer('name: "m" type: "MoE" bottom: "x" top: "y"',
                       [(4, 8)])


class TestLayerNorm:
    def test_matches_manual(self, rng):
        layer, params, state = make_layer(
            'name: "ln" type: "LayerNorm" bottom: "x" top: "y"', [(2, 5, 8)])
        x = rand((2, 5, 8), rng)
        (y,), _ = layer.apply(params, state, [x], train=True, rng=None)
        xn = np.asarray(x)
        mean = xn.mean(-1, keepdims=True)
        var = xn.var(-1, keepdims=True)
        ref = (xn - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)

    def test_gradients(self, rng):
        layer, params, state = make_layer(
            'name: "ln" type: "LayerNorm" bottom: "x" top: "y"\n'
            'layer_norm_param { eps: 0.001 }', [(2, 3, 6)])
        check_gradients(layer, params, state, [rand((2, 3, 6), rng)])

    def test_no_scale_bias(self, rng):
        layer, params, state = make_layer(
            'name: "ln" type: "LayerNorm" bottom: "x" top: "y"\n'
            'layer_norm_param { scale_bias: false }', [(2, 8)])
        assert params == {}


class TestTransformerLM:
    def test_zoo_model_builds(self):
        """The generated models/transformer_lm prototxts build (train_val
        and deploy) — the long-context stack from the declarative surface."""
        from caffe_mpi_tpu.net import Net
        net = Net(NetParameter.from_file(
            "models/transformer_lm/train_val.prototxt"), phase="TRAIN")
        assert net.blob_shapes["logits"] == (8, 64, 256)
        types = {l.lp.type for l in net.layers}
        assert {"Embed", "Attention", "MoE", "LayerNorm",
                "Eltwise"} <= types
        Net(NetParameter.from_file(
            "models/transformer_lm/deploy.prototxt"), phase="TEST")

    def test_induction_task_convergence(self, rng):
        """A tiny LM learns 'x[t+1] = x[t-3]' (period-4 copy) to >=90%
        held-out next-token accuracy — a task that REQUIRES attending
        backwards, so it proves the causal-attention training path, not
        just the FFN."""
        import sys
        sys.path.insert(0, "models")
        from generate_models import transformer_lm
        text = transformer_lm(batch=8, seq=32, vocab=32, dim=32, heads=2,
                              n_blocks=1, ffn_hidden=64,
                              moe_experts=4).to_prototxt()
        sp = SolverParameter.from_text(
            'base_lr: 0.003 momentum: 0.9 momentum2: 0.999 type: "Adam" '
            'lr_policy: "fixed" max_iter: 400 display: 0')
        sp.net_param = NetParameter.from_text(text)
        solver = Solver(sp)

        B, S, V = 8, 32, 32

        def feed(it):
            r = np.random.RandomState(it)
            base = r.randint(0, V, (B, 4))
            seq = np.tile(base, (1, S // 4 + 2))[:, :S + 1]
            return {"tokens": jnp.asarray(seq[:, :S]),
                    "label": jnp.asarray(seq[:, 1:S + 1])}

        solver.step(300, feed)
        f = feed(10_001)
        blobs, _, _ = solver.net.apply(solver.params, solver.net_state, f,
                                       train=False)
        pred = np.asarray(jnp.argmax(blobs["logits"], axis=-1))
        lab = np.asarray(f["label"])
        acc = (pred[:, 8:] == lab[:, 8:]).mean()
        assert acc >= 0.9, acc


class TestSequenceBf16:
    def test_transformer_block_under_float16_policy(self, rng):
        """Attention/LayerNorm/MoE under default_forward_type FLOAT16
        (bf16 on TPU): activations run bf16, loss stays finite, and the
        bf16 forward tracks the f32 one."""
        net_text = """
        default_forward_type: FLOAT16
        default_backward_type: FLOAT16
        layer { name: "in" type: "Input" top: "x" top: "t"
                input_param { shape { dim: 2 dim: 8 dim: 16 }
                              shape { dim: 2 dim: 8 } } }
        layer { name: "ln" type: "LayerNorm" bottom: "x" top: "h1" }
        layer { name: "attn" type: "Attention" bottom: "h1" top: "h2"
                attention_param { num_heads: 2 causal: true } }
        layer { name: "moe" type: "MoE" bottom: "h2" top: "h3"
                moe_param { num_experts: 2 hidden_dim: 32
                            capacity_factor: 8.0 } }
        layer { name: "ip" type: "InnerProduct" bottom: "h3" top: "y"
                inner_product_param { num_output: 4 axis: 2
                  weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t"
                top: "l" softmax_param { axis: 2 } }
        """
        from caffe_mpi_tpu.net import Net
        net16 = Net(NetParameter.from_text(net_text), phase="TRAIN")
        net32 = Net(NetParameter.from_text(
            net_text.replace("default_forward_type: FLOAT16\n", "")
                    .replace("default_backward_type: FLOAT16\n", "")),
            phase="TRAIN")
        p, s = net16.init(jax.random.PRNGKey(0))
        p32, s32 = net32.init(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16)
                        .astype(np.float32))
        t = jnp.asarray(np.random.RandomState(1).randint(0, 4, (2, 8)))
        blobs16, _, l16 = net16.apply(p, s, {"x": x, "t": t}, train=True,
                                      rng=jax.random.PRNGKey(2))
        blobs32, _, l32 = net32.apply(p32, s32, {"x": x, "t": t},
                                      train=True, rng=jax.random.PRNGKey(2))
        assert blobs16["h2"].dtype == jnp.bfloat16
        assert np.isfinite(float(l16))
        np.testing.assert_allclose(float(l16), float(l32), rtol=0.05)


class TestMoELayer:
    TEXT = ('name: "moe" type: "MoE" bottom: "x" top: "y" top: "aux"\n'
            'loss_weight: 0 loss_weight: 0.01\n'
            'moe_param { num_experts: 4 hidden_dim: 32 top_k: 1 '
            'capacity_factor: 8.0 }')

    def test_matches_ops_moe(self, rng):
        from caffe_mpi_tpu.ops.moe import moe_ffn_dense_reference
        layer, params, state = make_layer(self.TEXT, [(16, 8)])
        x = rand((16, 8), rng)
        (y, aux), _ = layer.apply(params, state, [x], train=True, rng=None)
        ref = moe_ffn_dense_reference(
            {k: jnp.asarray(v) for k, v in params.items()}, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
        assert np.isfinite(float(aux))

    def test_sequence_input(self, rng):
        layer, params, state = make_layer(self.TEXT, [(2, 6, 8)])
        x = rand((2, 6, 8), rng)
        (y, aux), _ = layer.apply(params, state, [x], train=True, rng=None)
        assert y.shape == (2, 6, 8)

    def test_trains_with_aux_loss_in_net(self, rng):
        """Full prototxt surface: MoE inside a Net, aux top weighted into
        the loss, trains through the Solver."""
        net_text = """
        name: "moenet"
        layer { name: "in" type: "Input" top: "x" top: "t"
                input_param { shape { dim: 16 dim: 8 } shape { dim: 16 } } }
        layer { name: "moe1" type: "MoE" bottom: "x" top: "h" top: "moe_aux"
                loss_weight: 0 loss_weight: 0.01
                moe_param { num_experts: 4 hidden_dim: 32
                            capacity_factor: 8.0 } }
        layer { name: "ip" type: "InnerProduct" bottom: "h" top: "y"
                inner_product_param { num_output: 4
                  weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t"
                top: "l" }
        """
        sp = SolverParameter.from_text(
            'base_lr: 0.1 momentum: 0.9 lr_policy: "fixed" max_iter: 100 '
            'display: 0 type: "SGD"')
        sp.net_param = NetParameter.from_text(net_text)
        solver = Solver(sp)
        templates = rng.randn(4, 8).astype(np.float32)

        def feed(it):
            r = np.random.RandomState(it % 8)
            t = r.randint(0, 4, 16)
            return {"x": jnp.asarray(templates[t]
                                     + 0.2 * r.randn(16, 8).astype(np.float32)),
                    "t": jnp.asarray(t)}

        first = float(solver.step(1, feed))
        last = float(solver.step(80, feed))
        assert last < first * 0.5, (first, last)

    def test_expert_parallel_via_solver_shardings(self, rng):
        """EP from the training surface: per-param dict rules shard the
        expert banks over 'model'; training matches the replicated run."""
        from caffe_mpi_tpu.parallel import MeshPlan
        net_text = """
        layer { name: "in" type: "Input" top: "x" top: "t"
                input_param { shape { dim: 16 dim: 8 } shape { dim: 16 } } }
        layer { name: "moe1" type: "MoE" bottom: "x" top: "h"
                moe_param { num_experts: 4 hidden_dim: 16
                            capacity_factor: 8.0 } }
        layer { name: "ip" type: "InnerProduct" bottom: "h" top: "y"
                inner_product_param { num_output: 4
                  weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t"
                top: "l" }
        """
        data = []
        r = np.random.RandomState(3)
        for _ in range(4):
            data.append({"x": jnp.asarray(r.randn(16, 8).astype(np.float32)),
                         "t": jnp.asarray(r.randint(0, 4, 16))})

        def ms(shardings):
            sp = SolverParameter.from_text(
                'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" '
                'max_iter: 8 type: "SGD" random_seed: 7')
            sp.net_param = NetParameter.from_text(net_text)
            return Solver(sp, mesh=MeshPlan.from_shape(data=2, model=4),
                          param_shardings=shardings)

        ep = {"moe1": {"w1": ("model",), "b1": ("model",),
                       "w2": ("model",), "b2": ("model",)}}
        s_ep = ms(ep)
        s_rep = ms(None)
        assert not s_ep.params["moe1"]["w1"].sharding.is_fully_replicated
        s_ep.step(6, lambda it: data[it % 4])
        s_rep.step(6, lambda it: data[it % 4])
        np.testing.assert_allclose(np.array(s_ep.params["moe1"]["w1"]),
                                   np.array(s_rep.params["moe1"]["w1"]),
                                   rtol=2e-4, atol=1e-6)