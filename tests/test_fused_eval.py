"""Fused, device-fed, asynchronous evaluation (ISSUE 2).

The contract under test: the fused eval pipeline is an EXECUTION-
SCHEDULE change, not a semantics change. One jitted `lax.scan` over a
[T, B, ...] test super-batch (accumulators carried in HBM, chunks
chained through the program's acc0 input) must produce scores BITWISE
equal on the CPU backend to the classic one-dispatch-per-test-batch
loop it replaces, across: direct test_all calls, in-training boundaries
(including test_initialization), multiple test nets with different
test_iter, snapshot/resume across a test boundary, mesh-sharded (SPMD)
eval feeds, and the gpipe stage-0 eval path. Dispatch accounting: a
pass over test_iter batches costs <= ceil(test_iter/T) + 1 device
dispatches (the +1 is the shared-param on-device copy that decouples
eval from the donating train step).
"""

import logging
import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.proto import SolverParameter
from caffe_mpi_tpu.proto.config import NetParameter
from caffe_mpi_tpu.solver import Solver

CLS_NET = """
name: "cls"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 8 dim: 6 } shape { dim: 8 } } }
layer { name: "ip" type: "InnerProduct" bottom: "x" top: "y"
        inner_product_param { num_output: 3
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t"
        top: "l" include { phase: TRAIN } }
layer { name: "acc" type: "Accuracy" bottom: "y" bottom: "t"
        top: "acc" include { phase: TEST } }
"""

# second test topology: TWO output blobs (loss + accuracy) in TEST phase
CLS_NET_LOSS_ACC = CLS_NET.replace(
    'top: "l" include { phase: TRAIN } }', 'top: "l" }')

BASE = ('base_lr: 0.2 lr_policy: "fixed" max_iter: 1000 type: "SGD" '
        'momentum: 0.9 display: 0 random_seed: 11 ')


def make_solver(extra: str = "", net: str = CLS_NET, test_nets=None, **kw):
    sp = SolverParameter.from_text(BASE + extra)
    sp.net_param = NetParameter.from_text(net)
    if test_nets is not None:
        sp.test_net_param = [NetParameter.from_text(t) for t in test_nets]
    return Solver(sp, **kw)


def cls_feed(seed_base: int = 0, batch: int = 8):
    """Deterministic separable-cluster batches, a pure function of the
    index (the feed_fn protocol the DeviceFeedQueue relies on)."""
    templates = np.random.RandomState(99).randn(3, 6).astype(np.float32)

    def feed(it):
        r = np.random.RandomState(seed_base + it)
        t = r.randint(0, 3, batch)
        x = templates[t] + 0.1 * r.randn(batch, 6).astype(np.float32)
        return {"x": np.asarray(x, np.float32), "t": t.astype(np.int32)}
    return feed


def classic_scores(solver, ti, feed_fn, iters):
    """The pre-ISSUE-2 evaluation loop, reimplemented verbatim as the
    oracle: one jitted forward per test batch, device-chained adds, one
    host transfer at the end."""
    tnet = solver.test_nets[ti]
    out_blobs = tuple(Solver._output_blobs(tnet))

    @jax.jit
    def fwd(p, s, f):
        blobs = tnet.apply(p, s, f, train=False)[0]
        return jnp.stack([jnp.sum(blobs[b]).astype(jnp.float32)
                          for b in out_blobs])

    tparams = solver._shared_params(tnet)
    tstate = solver.net_state
    acc = None
    for k in range(iters):
        sums = fwd(tparams, tstate, feed_fn(k))
        acc = sums if acc is None else acc + sums
    vals = np.asarray(acc) / iters
    return {b: float(v) for b, v in zip(out_blobs, vals)}


class TestBitwiseEquivalence:
    def test_direct_test_all_matches_classic(self):
        s = make_solver("test_iter: 4")
        train, test = cls_feed(0), cls_feed(5000)
        s.step(3, train)
        scores = s.test_all([test])
        assert s._pending_eval is None  # sync wrapper fully drains
        oracle = classic_scores(s, 0, test, 4)
        assert scores[0] == oracle  # bitwise: dict of exact floats

    def test_multi_chunk_pass_matches_classic(self):
        """ceil(test_iter/T) > 1: the accumulator chains ACROSS eval
        dispatches through acc0 in exactly the classic addition order."""
        s = make_solver("test_iter: 7 test_chunk: 3")
        train, test = cls_feed(0), cls_feed(7000)
        s.step(2, train)
        d0 = s.test_dispatch_count
        scores = s.test_all([test])
        assert scores[0] == classic_scores(s, 0, test, 7)
        # 1 param copy + ceil(7/3) = 3 scan chunks
        assert s.test_dispatch_count - d0 == 4
        assert s.test_dispatch_count - d0 <= math.ceil(7 / 3) + 1

    def test_multiple_test_nets_different_test_iter(self):
        s = make_solver("test_iter: 3 test_iter: 5 test_chunk: 2",
                        test_nets=[CLS_NET, CLS_NET_LOSS_ACC])
        train = cls_feed(0)
        feeds = [cls_feed(5000), cls_feed(6000)]
        s.step(2, train)
        d0, p0 = s.test_dispatch_count, s.test_pass_count
        scores = s.test_all(feeds)
        assert scores[0] == classic_scores(s, 0, feeds[0], 3)
        assert scores[1] == classic_scores(s, 1, feeds[1], 5)
        assert set(scores[1]) == {"l", "acc"}
        assert s.test_pass_count - p0 == 2
        # net0: 1 copy + ceil(3/2)=2; net1: 1 copy + ceil(5/2)=3
        assert s.test_dispatch_count - d0 == 7

    def test_degenerate_test_net(self):
        s = make_solver("test_iter: 0")
        assert s.test_all([cls_feed(1)]) == [{}]


class TestChunkSizing:
    def test_explicit_knob_pins_t(self):
        s = make_solver("test_iter: 6 test_chunk: 4")
        assert s._test_chunk_len(s.test_nets[0], 6) == 4
        assert s._test_chunk_len(s.test_nets[0], 3) == 3  # capped by iters

    def test_auto_t_respects_hbm_budget(self):
        s = make_solver("test_iter: 50")
        tnet = s.test_nets[0]
        # default budget: T limited only by iters and the scan-length cap
        assert s._test_chunk_len(tnet, 50) == 50
        assert s._test_chunk_len(tnet, 500) == 64
        # batch bytes: x [8,6] f32 + t [8] int = 224; a 500-byte budget
        # fits 2 batches per super-batch
        s._TEST_SUPER_BATCH_BYTES = 500
        assert s._test_chunk_len(tnet, 50) == 2


class TestAsyncInTraining:
    def test_boundary_scores_and_iteration_tags(self, caplog):
        """Evaluation at test boundaries (incl. test_initialization at
        iter 0) runs async but logs bitwise-classic scores tagged with
        the iteration they evaluate."""
        cfg = ("test_iter: 2 test_interval: 4 test_initialization: true ")
        a = make_solver(cfg)
        train, test = cls_feed(0), cls_feed(5000)
        with caplog.at_level(logging.INFO, "caffe_mpi_tpu.solver"):
            a.step(8, train, test_feed_fns=[test])
        headers = [r.args for r in caplog.records
                   if r.msg.startswith("Test net #%d, iteration")]
        assert headers == [(0, 0), (0, 4)]
        logged = [r.args for r in caplog.records
                  if r.msg.startswith("    Test net")]
        assert [a_[1] for a_ in logged] == ["acc", "acc"]

        # twin without eval: identical training trajectory, classic
        # scores computed synchronously at the same iterations
        b = make_solver(cfg)
        want = [classic_scores(b, 0, test, 2)["acc"]]
        b.step(4, train)
        want.append(classic_scores(b, 0, test, 2)["acc"])
        assert [a_[2] for a_ in logged] == want  # bitwise

    def test_async_eval_does_not_perturb_training(self):
        """With step_chunk dividing test_interval the chunk schedule is
        identical with and without test feeds — so params must be
        BITWISE identical: the async eval copies its param view and
        never touches train state."""
        cfg = "test_iter: 3 test_interval: 4 step_chunk: 2 " \
              "test_initialization: false "
        a = make_solver(cfg)
        b = make_solver(cfg)
        train, test = cls_feed(0), cls_feed(5000)
        a.step(8, train, test_feed_fns=[test])
        b.step(8, train)
        assert a.iter == b.iter == 8
        for ln in a.params:
            for pn in a.params[ln]:
                np.testing.assert_array_equal(
                    np.asarray(a.params[ln][pn]), np.asarray(b.params[ln][pn]),
                    err_msg=f"params {ln}/{pn}")
        # both boundaries fired and were harvested inside step()
        assert a.test_pass_count == 1  # boundary at iter 4 only (8 = end)
        assert a._pending_eval is None

    def test_boundary_dispatches_only_first_chunk(self):
        """_start_eval returns after chunk 0: the remaining chunks
        dispatch from _continue_eval between train chunks (or at
        harvest), so the boundary stall is one dispatch + the param
        copy, not the pass."""
        s = make_solver("test_iter: 6 test_chunk: 2")
        test = cls_feed(5000)
        d0 = s.test_dispatch_count
        s._start_eval([test])
        entry = s._pending_eval["entries"][0]
        assert entry["next"] == 2  # chunk 0 only
        assert s.test_dispatch_count - d0 == 2  # copy + first scan
        # the worker is assembling chunk 1 (the hint) in the background
        scores = s._harvest_eval()  # drains chunks 1..2, then syncs
        assert s.test_dispatch_count - d0 == 4
        assert scores[0] == classic_scores(s, 0, test, 6)

    def test_continue_eval_dispatches_ready_chunks(self):
        s = make_solver("test_iter: 4 test_chunk: 2")
        test = cls_feed(5000)
        s._start_eval([test])
        entry = s._pending_eval["entries"][0]
        # wait for the hinted chunk-1 assembly, then the non-blocking
        # advance must dispatch it
        entry["queue"]._pending[(2, 2)].result()
        s._continue_eval()
        assert entry["next"] == 4
        scores = s._harvest_eval()
        assert scores[0] == classic_scores(s, 0, test, 4)

    def test_no_prefetch_at_max_iter(self):
        """Training that ENDS on a test boundary must not assemble a
        super-batch nobody will consume."""
        s = make_solver("test_iter: 2 test_interval: 4 "
                        "test_initialization: false")
        s.sp.max_iter = 4
        train, test = cls_feed(0), cls_feed(5000)
        s.step(4, train, test_feed_fns=[test])
        assert s.iter == 4 == s.sp.max_iter
        q = s._test_feed_queues.get(0)
        assert q is None or not q._pending

    def test_eval_stall_is_tracked(self):
        s = make_solver("test_iter: 2 test_interval: 2 "
                        "test_initialization: false")
        train, test = cls_feed(0), cls_feed(5000)
        s.step(4, train, test_feed_fns=[test])
        assert s.test_pass_count == 1
        assert s.eval_stall_ms > 0.0

    def test_snapshot_resume_across_test_boundary(self, tmp_path):
        """snapshot at 6 with a test boundary at 4 and step_chunk 4:
        resuming must continue the uninterrupted trajectory and the
        post-resume evals must match the classic oracle."""
        cfg = ('type: "Adam" test_iter: 2 test_interval: 4 snapshot: 6 '
               'test_initialization: false step_chunk: 4 ')
        train, test = cls_feed(0), cls_feed(5000)
        a = make_solver(cfg)
        a.sp.snapshot_prefix = str(tmp_path / "fe")
        a.step(10, train, test_feed_fns=[test])
        a.wait_snapshots()

        c = make_solver(cfg)
        c.restore(str(tmp_path / "fe_iter_6.solverstate"))
        assert c.iter == 6
        c.step(4, train, test_feed_fns=[test])
        for ln in a.params:
            for pn in a.params[ln]:
                np.testing.assert_allclose(
                    np.asarray(a.params[ln][pn]),
                    np.asarray(c.params[ln][pn]),
                    rtol=1e-6, atol=1e-7, err_msg=f"params {ln}/{pn}")
        scores = c.test_all([test])
        assert scores[0] == classic_scores(c, 0, test, 2)


class TestParallelEval:
    def test_mesh_sharded_eval_matches_single_device(self):
        """SPMD runs now evaluate on all chips: the test super-batch
        shards over 'data' (batch axis 2 of [T, 1, B, ...]) and the
        scores match a meshless twin."""
        from caffe_mpi_tpu.parallel import MeshPlan
        train, test = cls_feed(0), cls_feed(5000)
        a = make_solver("test_iter: 4")
        b = make_solver("test_iter: 4", mesh=MeshPlan.data_parallel())
        a.step(2, train)
        b.step(2, train)
        sa = a.test_all([test])
        sb = b.test_all([test])
        assert sb[0].keys() == sa[0].keys()
        for k in sa[0]:
            assert sb[0][k] == pytest.approx(sa[0][k], rel=1e-5, abs=1e-6)
        # the eval feed queue really placed via the mesh
        assert b._test_feed_queues[0].place is not None
        # feeds were sharded, not replicated (batch 8 divides n_data 8)
        assert not b._warned_unsharded_test

    def test_mesh_indivisible_test_batch_replicates(self):
        """A test batch that doesn't divide the 'data' axis falls back
        to replicated evaluation instead of crashing (the pre-ISSUE-2
        behavior for ALL mesh test feeds)."""
        from caffe_mpi_tpu.parallel import MeshPlan
        net = CLS_NET.replace("dim: 8 dim: 6", "dim: 4 dim: 6") \
                     .replace("shape { dim: 8 }", "shape { dim: 4 }")
        test = cls_feed(5000, batch=4)
        a = make_solver("test_iter: 3", net=net)
        b = make_solver("test_iter: 3", net=net,
                        mesh=MeshPlan.data_parallel())
        sa = a.test_all([test])
        sb = b.test_all([test])
        assert b._warned_unsharded_test
        for k in sa[0]:
            assert sb[0][k] == pytest.approx(sa[0][k], rel=1e-5, abs=1e-6)

    def test_shard_feeds_or_replicate(self):
        from caffe_mpi_tpu.parallel import MeshPlan
        mesh = MeshPlan.data_parallel()
        tree = {"x": np.zeros((2, 1, 8, 6), np.float32)}
        placed, sharded = mesh.shard_feeds_or_replicate(tree, batch_axis=2)
        assert sharded
        assert placed["x"].sharding.spec == jax.sharding.PartitionSpec(
            None, None, "data", None)
        odd = {"x": np.zeros((2, 1, 6, 6), np.float32)}
        placed, sharded = mesh.shard_feeds_or_replicate(odd, batch_axis=2)
        assert not sharded
        assert placed["x"].sharding.spec == jax.sharding.PartitionSpec()

    def test_gpipe_stage0_eval(self):
        """Stage-placed params evaluate whole-net on stage-0's device
        through the same fused pipeline; scores are deterministic and
        match the sequential solver's within the gpipe trajectory
        tolerance."""
        train_full, test = cls_feed(0), cls_feed(5000)
        halves = lambda it: {k: v[4 * (it % 2):4 * (it % 2) + 4]
                             for k, v in train_full(it // 2).items()}
        seq = make_solver("test_iter: 3")
        seq.step(2, train_full)
        gp = make_solver("test_iter: 3", gpipe={"stages": 2, "micro": 2})
        gp.step(2, lambda it: halves(it))
        s1 = gp.test_all([test])
        s2 = gp.test_all([test])
        assert s1 == s2  # deterministic
        ref = seq.test_all([test])
        for k in ref[0]:
            assert s1[0][k] == pytest.approx(ref[0][k], rel=5e-4, abs=1e-5)


class TestCLI:
    def test_test_chunk_flag_parses(self):
        from caffe_mpi_tpu.tools.cli import _parser
        for spelling in ("--test-chunk", "--test_chunk", "-test_chunk"):
            args = _parser().parse_args(
                ["train", "-solver", "s.prototxt", spelling, "3"])
            assert args.test_chunk == 3
        assert _parser().parse_args(
            ["train", "-solver", "s.prototxt"]).test_chunk == 0


class TestFeedQueuePrefetch:
    def test_prefetch_builds_ahead_without_blocking(self):
        from caffe_mpi_tpu.data.feeder import DeviceFeedQueue
        calls = []

        def feed(it):
            calls.append(it)
            return {"x": np.full((4, 3), it, np.float32)}

        q = DeviceFeedQueue(feed, iter_size=1)
        try:
            q.prefetch(0, 3)
            q._pending[(0, 3)].result()  # worker built it
            n = len(calls)
            out = q.get(0, 3)  # served from the prefetch, no rebuild
            assert len(calls) == n
            assert out["x"].shape == (3, 1, 4, 3)
            q.prefetch(3, 2)
            q.prefetch(3, 2)  # idempotent
            assert len(q._pending) == 1
        finally:
            q.close()

    def test_boundary_prefetch_warms_test_queue(self):
        """Training toward a test boundary schedules the first eval
        super-batch on the worker before the boundary iteration."""
        s = make_solver("test_iter: 2 test_interval: 3 "
                        "test_initialization: false")
        train, test = cls_feed(0), cls_feed(5000)
        s.step(3, train, test_feed_fns=[test])  # ends AT the boundary
        q = s._test_feed_queues.get(0)
        assert q is not None and (0, 2) in q._pending
        s.step(3, train, test_feed_fns=[test])  # consumes it at iter 3
        assert s.test_pass_count == 1
