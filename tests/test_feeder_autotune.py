"""Prefetch auto-tuning (reference data_layer.cpp:46-113).

The reference sizes parser/transformer thread counts at iteration 0 from
free GPU memory and net cost; the Feeder's analogue re-sizes the
lookahead window from measured batch-build time vs consumer step time,
bounded by a host-RAM budget for in-flight batches. threads=0 (the
prototxt default, caffe.proto:840) enables it; explicit threads>0 pins
the knobs, like the reference's explicit threads+parser_threads pair.
"""

import time

import numpy as np

from caffe_mpi_tpu.data.feeder import _LOOKAHEAD_HARD_CAP, Feeder


class _TimedDataset:
    """Synthetic dataset with a controllable per-record cost."""

    def __init__(self, n=4096, delay=0.0, shape=(3, 8, 8)):
        self.n = n
        self.delay = delay
        self.shape = shape

    def __len__(self):
        return self.n

    def get(self, i):
        if self.delay:
            time.sleep(self.delay)
        img = np.full(self.shape, i % 251, np.uint8)
        return img, i % 10


def _drive(feeder, iters, step_time=0.0):
    for it in range(iters):
        feeder(it)
        if step_time:
            time.sleep(step_time)
    feeder.close()


def test_slow_builds_grow_lookahead():
    # building a batch takes ~8ms (4 records x 2ms), consumer is
    # immediate -> supply must run many batches ahead
    ds = _TimedDataset(delay=0.002)
    f = Feeder(ds, None, batch_size=4, threads=0, lookahead=1)
    assert f.auto
    _drive(f, 16)
    assert f.lookahead > 1


def test_fast_builds_shrink_lookahead():
    # building is instant, consumer sleeps 5ms per step -> one batch of
    # lookahead suffices; an oversized initial window contracts
    ds = _TimedDataset(delay=0.0)
    f = Feeder(ds, None, batch_size=2, threads=0, lookahead=12)
    _drive(f, 16, step_time=0.005)
    assert f.lookahead <= 3


def test_memory_budget_caps_lookahead():
    # batch = 4 x 3x8x8 uint8 + labels ~= 800 B; budget of 3 batches
    # caps the window at 2 regardless of the build/step ratio
    ds = _TimedDataset(delay=0.002)
    f = Feeder(ds, None, batch_size=4, threads=0, lookahead=1,
               mem_budget=3 * (4 * 3 * 8 * 8 + 4 * 4))
    _drive(f, 16)
    assert 1 <= f.lookahead <= 2


def test_hard_cap():
    ds = _TimedDataset(delay=0.002)
    f = Feeder(ds, None, batch_size=4, threads=0, lookahead=1)
    _drive(f, 16)
    assert f.lookahead <= _LOOKAHEAD_HARD_CAP


def test_explicit_threads_disable_tuning():
    ds = _TimedDataset(delay=0.002)
    f = Feeder(ds, None, batch_size=4, threads=2, lookahead=3)
    assert not f.auto
    _drive(f, 16)
    assert f.lookahead == 3 and f.threads == 2


def test_auto_mode_is_deterministic():
    # tuning changes scheduling, never record->slot assignment
    ds = _TimedDataset(delay=0.001)
    a = Feeder(ds, None, batch_size=4, threads=0, lookahead=1,
               shuffle=True, seed=7)
    b = Feeder(ds, None, batch_size=4, threads=3, lookahead=8,
               shuffle=True, seed=7)
    batches_a = [a(i) for i in range(12)]
    batches_b = [b(i) for i in range(12)]
    a.close(), b.close()
    for fa, fb in zip(batches_a, batches_b):
        for k in fa:
            np.testing.assert_array_equal(fa[k], fb[k])
