#!/usr/bin/env python
"""Model zoo generator — programmatically emits the prototxt zoo using
NetSpec (the reference keeps equivalent python generators in
models/modelBuilder/). Run from the repo root:

    python models/generate_models.py

Topologies follow the reference zoo: bvlc_alexnet, CIFAR-10 quick,
GoogLeNet (inception v1), ResNet-50 (bottleneck [3,4,6,3], NVCaffe
fused-scale BatchNorm). Inputs are Input layers (feed-based); the data
pipeline binds real datasets at run time.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from caffe_mpi_tpu.net_spec import L, NetSpec


def train_test_tail(n, logits, include_train_loss=True):
    n.loss = L.SoftmaxWithLoss(logits, n.label,
                               include=dict(phase="TRAIN"))
    n.accuracy = L.Accuracy(logits, n.label, include=dict(phase="TEST"))
    n.accuracy_top5 = L.Accuracy(logits, n.label, top_k=5,
                                 include=dict(phase="TEST"))


def conv_relu(bottom, nout, ks, stride=1, pad=0, group=1):
    c = L.Convolution(bottom, num_output=nout, kernel_size=ks, stride=stride,
                      pad=pad, group=group,
                      weight_filler=dict(type="gaussian", std=0.01),
                      bias_filler=dict(type="constant"),
                      param=[dict(lr_mult=1, decay_mult=1),
                             dict(lr_mult=2, decay_mult=0)])
    return c, L.ReLU(c, in_place=True)


def alexnet(batch=256):
    """bvlc_alexnet topology (reference models/bvlc_alexnet)."""
    n = NetSpec("AlexNet")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 227, 227]), dict(dim=[batch])]))
    n.conv1, n.relu1 = conv_relu(n.data, 96, 11, stride=4)
    n.norm1 = L.LRN(n.relu1, local_size=5, alpha=1e-4, beta=0.75)
    n.pool1 = L.Pooling(n.norm1, pool="MAX", kernel_size=3, stride=2)
    n.conv2, n.relu2 = conv_relu(n.pool1, 256, 5, pad=2, group=2)
    n.norm2 = L.LRN(n.relu2, local_size=5, alpha=1e-4, beta=0.75)
    n.pool2 = L.Pooling(n.norm2, pool="MAX", kernel_size=3, stride=2)
    n.conv3, n.relu3 = conv_relu(n.pool2, 384, 3, pad=1)
    n.conv4, n.relu4 = conv_relu(n.relu3, 384, 3, pad=1, group=2)
    n.conv5, n.relu5 = conv_relu(n.relu4, 256, 3, pad=1, group=2)
    n.pool5 = L.Pooling(n.relu5, pool="MAX", kernel_size=3, stride=2)
    n.fc6 = L.InnerProduct(n.pool5, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant", value=0.1))
    n.relu6 = L.ReLU(n.fc6, in_place=True)
    n.drop6 = L.Dropout(n.fc6, dropout_ratio=0.5, in_place=True)
    n.fc7 = L.InnerProduct(n.fc6, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant", value=0.1))
    n.relu7 = L.ReLU(n.fc7, in_place=True)
    n.drop7 = L.Dropout(n.fc7, dropout_ratio=0.5, in_place=True)
    n.fc8 = L.InnerProduct(n.fc7, num_output=1000,
                           weight_filler=dict(type="gaussian", std=0.01),
                           bias_filler=dict(type="constant"))
    train_test_tail(n, n.fc8)
    return n


def cifar10_quick(batch=100):
    """CIFAR-10 quick (reference examples/cifar10)."""
    n = NetSpec("CIFAR10_quick")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 32, 32]), dict(dim=[batch])]))
    n.conv1 = L.Convolution(n.data, num_output=32, kernel_size=5, pad=2,
                            weight_filler=dict(type="gaussian", std=0.0001),
                            param=[dict(lr_mult=1), dict(lr_mult=2)])
    n.pool1 = L.Pooling(n.conv1, pool="MAX", kernel_size=3, stride=2)
    n.relu1 = L.ReLU(n.pool1, in_place=True)
    n.conv2 = L.Convolution(n.pool1, num_output=32, kernel_size=5, pad=2,
                            weight_filler=dict(type="gaussian", std=0.01),
                            param=[dict(lr_mult=1), dict(lr_mult=2)])
    n.relu2 = L.ReLU(n.conv2, in_place=True)
    n.pool2 = L.Pooling(n.conv2, pool="AVE", kernel_size=3, stride=2)
    n.conv3 = L.Convolution(n.pool2, num_output=64, kernel_size=5, pad=2,
                            weight_filler=dict(type="gaussian", std=0.01),
                            param=[dict(lr_mult=1), dict(lr_mult=2)])
    n.relu3 = L.ReLU(n.conv3, in_place=True)
    n.pool3 = L.Pooling(n.conv3, pool="AVE", kernel_size=3, stride=2)
    n.ip1 = L.InnerProduct(n.pool3, num_output=64,
                           weight_filler=dict(type="gaussian", std=0.1),
                           param=[dict(lr_mult=1), dict(lr_mult=2)])
    n.ip2 = L.InnerProduct(n.ip1, num_output=10,
                           weight_filler=dict(type="gaussian", std=0.1),
                           param=[dict(lr_mult=1), dict(lr_mult=2)])
    train_test_tail(n, n.ip2)
    return n


def inception(n, name, bottom, o1, o3r, o3, o5r, o5, op):
    """GoogLeNet inception module."""
    def cr(branch, b, nout, ks, pad=0):
        c = L.Convolution(b, num_output=nout, kernel_size=ks, pad=pad,
                          weight_filler=dict(type="xavier"),
                          bias_filler=dict(type="constant", value=0.2),
                          param=[dict(lr_mult=1, decay_mult=1),
                                 dict(lr_mult=2, decay_mult=0)])
        r = L.ReLU(c, in_place=True)
        setattr(n, f"{name}_{branch}", c)
        setattr(n, f"{name}_relu_{branch}", r)
        return r

    c1 = cr("1x1", bottom, o1, 1)
    c3r = cr("3x3_reduce", bottom, o3r, 1)
    c3 = cr("3x3", c3r, o3, 3, pad=1)
    c5r = cr("5x5_reduce", bottom, o5r, 1)
    c5 = cr("5x5", c5r, o5, 5, pad=2)
    pool = L.Pooling(bottom, pool="MAX", kernel_size=3, stride=1, pad=1)
    setattr(n, f"{name}_pool", pool)
    cp = cr("pool_proj", pool, op, 1)
    out = L.Concat(c1, c3, c5, cp)
    setattr(n, f"{name}_output", out)
    return out


def googlenet(batch=128):
    """bvlc_googlenet topology (reference models/bvlc_googlenet), without
    the aux classifier heads (NVCaffe's training recipe also drops them
    for large-batch runs)."""
    n = NetSpec("GoogLeNet")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 224, 224]), dict(dim=[batch])]))
    n.conv1, n.conv1_relu = conv_relu(n.data, 64, 7, stride=2, pad=3)
    n.pool1 = L.Pooling(n.conv1_relu, pool="MAX", kernel_size=3, stride=2)
    n.norm1 = L.LRN(n.pool1, local_size=5, alpha=1e-4, beta=0.75)
    n.conv2_reduce, n.conv2_reduce_relu = conv_relu(n.norm1, 64, 1)
    n.conv2, n.conv2_relu = conv_relu(n.conv2_reduce_relu, 192, 3, pad=1)
    n.norm2 = L.LRN(n.conv2_relu, local_size=5, alpha=1e-4, beta=0.75)
    n.pool2 = L.Pooling(n.norm2, pool="MAX", kernel_size=3, stride=2)
    x = inception(n, "inception_3a", n.pool2, 64, 96, 128, 16, 32, 32)
    x = inception(n, "inception_3b", x, 128, 128, 192, 32, 96, 64)
    n.pool3 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    x = inception(n, "inception_4a", n.pool3, 192, 96, 208, 16, 48, 64)
    x = inception(n, "inception_4b", x, 160, 112, 224, 24, 64, 64)
    x = inception(n, "inception_4c", x, 128, 128, 256, 24, 64, 64)
    x = inception(n, "inception_4d", x, 112, 144, 288, 32, 64, 64)
    x = inception(n, "inception_4e", x, 256, 160, 320, 32, 128, 128)
    n.pool4 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    x = inception(n, "inception_5a", n.pool4, 256, 160, 320, 32, 128, 128)
    x = inception(n, "inception_5b", x, 384, 192, 384, 48, 128, 128)
    n.pool5 = L.Pooling(x, pool="AVE", global_pooling=True)
    n.drop5 = L.Dropout(n.pool5, dropout_ratio=0.4, in_place=True)
    n.loss3_classifier = L.InnerProduct(
        n.pool5, num_output=1000, weight_filler=dict(type="xavier"),
        bias_filler=dict(type="constant"),
        param=[dict(lr_mult=1, decay_mult=1), dict(lr_mult=2, decay_mult=0)])
    train_test_tail(n, n.loss3_classifier)
    return n


def resnet50(batch=32, bf16=False):
    """ResNet-50, bottleneck [3,4,6,3], NVCaffe fused-scale BatchNorm
    (reference models/resnet50/train_val.prototxt)."""
    n = NetSpec("ResNet50")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 224, 224]), dict(dim=[batch])]))

    def conv_bn(b, nout, ks, stride=1, pad=0, relu=True):
        c = L.Convolution(b, num_output=nout, kernel_size=ks, stride=stride,
                          pad=pad, bias_term=False,
                          weight_filler=dict(type="msra"),
                          param=[dict(lr_mult=1, decay_mult=1)])
        bn = L.BatchNorm(c, scale_bias=True, eps=1e-5,
                         moving_average_fraction=0.9)
        if relu:
            return L.ReLU(bn, in_place=True), bn
        return bn, bn

    def bottleneck(b, nout, stride, project):
        if project:
            sc, _ = conv_bn(b, nout * 4, 1, stride=stride, relu=False)
        else:
            sc = b
        x, _ = conv_bn(b, nout, 1, stride=stride)
        x, _ = conv_bn(x, nout, 3, pad=1)
        x, _ = conv_bn(x, nout * 4, 1, relu=False)
        s = L.Eltwise(sc, x, operation="SUM")
        return L.ReLU(s, in_place=True)

    x, _ = conv_bn(n.data, 64, 7, stride=2, pad=3)
    n.conv1 = x
    n.pool1 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    x = n.pool1
    stages = [(64, 3), (128, 4), (256, 6), (512, 3)]
    for si, (nout, blocks) in enumerate(stages):
        for bi in range(blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            x = bottleneck(x, nout, stride, project=(bi == 0))
            setattr(n, f"res{si + 2}{chr(ord('a') + bi)}", x)
    n.pool5 = L.Pooling(x, pool="AVE", global_pooling=True)
    n.fc1000 = L.InnerProduct(n.pool5, num_output=1000,
                              weight_filler=dict(type="msra"),
                              bias_filler=dict(type="constant"),
                              param=[dict(lr_mult=1, decay_mult=1),
                                     dict(lr_mult=2, decay_mult=0)])
    train_test_tail(n, n.fc1000)
    return n


def caffenet(batch=256):
    """bvlc_reference_caffenet: AlexNet variant with pool-before-norm
    (reference models/bvlc_reference_caffenet)."""
    n = NetSpec("CaffeNet")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 227, 227]), dict(dim=[batch])]))
    n.conv1, n.relu1 = conv_relu(n.data, 96, 11, stride=4)
    n.pool1 = L.Pooling(n.relu1, pool="MAX", kernel_size=3, stride=2)
    n.norm1 = L.LRN(n.pool1, local_size=5, alpha=1e-4, beta=0.75)
    n.conv2, n.relu2 = conv_relu(n.norm1, 256, 5, pad=2, group=2)
    n.pool2 = L.Pooling(n.relu2, pool="MAX", kernel_size=3, stride=2)
    n.norm2 = L.LRN(n.pool2, local_size=5, alpha=1e-4, beta=0.75)
    n.conv3, n.relu3 = conv_relu(n.norm2, 384, 3, pad=1)
    n.conv4, n.relu4 = conv_relu(n.relu3, 384, 3, pad=1, group=2)
    n.conv5, n.relu5 = conv_relu(n.relu4, 256, 3, pad=1, group=2)
    n.pool5 = L.Pooling(n.relu5, pool="MAX", kernel_size=3, stride=2)
    n.fc6 = L.InnerProduct(n.pool5, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant", value=1))
    n.relu6 = L.ReLU(n.fc6, in_place=True)
    n.drop6 = L.Dropout(n.fc6, dropout_ratio=0.5, in_place=True)
    n.fc7 = L.InnerProduct(n.fc6, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant", value=1))
    n.relu7 = L.ReLU(n.fc7, in_place=True)
    n.drop7 = L.Dropout(n.fc7, dropout_ratio=0.5, in_place=True)
    n.fc8 = L.InnerProduct(n.fc7, num_output=1000,
                           weight_filler=dict(type="gaussian", std=0.01),
                           bias_filler=dict(type="constant"))
    train_test_tail(n, n.fc8)
    return n


def vgg16(batch=64):
    """VGG-16 (reference models/vgg16)."""
    n = NetSpec("VGG16")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 224, 224]), dict(dim=[batch])]))
    x = n.data
    cfg = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
    for bi, (reps, ch) in enumerate(cfg, start=1):
        for ri in range(1, reps + 1):
            c = L.Convolution(x, num_output=ch, kernel_size=3, pad=1,
                              weight_filler=dict(type="msra"),
                              bias_filler=dict(type="constant"),
                              param=[dict(lr_mult=1, decay_mult=1),
                                     dict(lr_mult=2, decay_mult=0)])
            r = L.ReLU(c, in_place=True)
            setattr(n, f"conv{bi}_{ri}", c)
            setattr(n, f"relu{bi}_{ri}", r)
            x = r
        p = L.Pooling(x, pool="MAX", kernel_size=2, stride=2)
        setattr(n, f"pool{bi}", p)
        x = p
    n.fc6 = L.InnerProduct(x, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant"))
    n.relu6 = L.ReLU(n.fc6, in_place=True)
    n.drop6 = L.Dropout(n.fc6, dropout_ratio=0.5, in_place=True)
    n.fc7 = L.InnerProduct(n.fc6, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant"))
    n.relu7 = L.ReLU(n.fc7, in_place=True)
    n.drop7 = L.Dropout(n.fc7, dropout_ratio=0.5, in_place=True)
    n.fc8 = L.InnerProduct(n.fc7, num_output=1000,
                           weight_filler=dict(type="gaussian", std=0.01),
                           bias_filler=dict(type="constant"))
    train_test_tail(n, n.fc8)
    return n


def resnet18(batch=64):
    """ResNet-18: basic blocks [2,2,2,2] (reference models/resnet18)."""
    n = NetSpec("ResNet18")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 224, 224]), dict(dim=[batch])]))

    def conv_bn(b, nout, ks, stride=1, pad=0, relu=True):
        c = L.Convolution(b, num_output=nout, kernel_size=ks, stride=stride,
                          pad=pad, bias_term=False,
                          weight_filler=dict(type="msra"),
                          param=[dict(lr_mult=1, decay_mult=1)])
        bn = L.BatchNorm(c, scale_bias=True, eps=1e-5,
                         moving_average_fraction=0.9)
        if relu:
            return L.ReLU(bn, in_place=True)
        return bn

    def basic_block(b, nout, stride, project):
        sc = conv_bn(b, nout, 1, stride=stride, relu=False) if project else b
        x = conv_bn(b, nout, 3, stride=stride, pad=1)
        x = conv_bn(x, nout, 3, pad=1, relu=False)
        return L.ReLU(L.Eltwise(sc, x, operation="SUM"), in_place=True)

    x = conv_bn(n.data, 64, 7, stride=2, pad=3)
    n.conv1 = x
    n.pool1 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    x = n.pool1
    for si, nout in enumerate([64, 128, 256, 512]):
        for bi in range(2):
            stride = 2 if (si > 0 and bi == 0) else 1
            x = basic_block(x, nout, stride,
                            project=(bi == 0 and si > 0))
            setattr(n, f"res{si + 2}{chr(ord('a') + bi)}", x)
    n.pool5 = L.Pooling(x, pool="AVE", global_pooling=True)
    n.fc1000 = L.InnerProduct(n.pool5, num_output=1000,
                              weight_filler=dict(type="msra"),
                              bias_filler=dict(type="constant"),
                              param=[dict(lr_mult=1, decay_mult=1),
                                     dict(lr_mult=2, decay_mult=0)])
    train_test_tail(n, n.fc1000)
    return n


SOLVERS = {
    "alexnet": """# AlexNet solver (reference models/bvlc_alexnet/solver.prototxt recipe)
net: "models/alexnet/train_val.prototxt"
test_iter: 1000
test_interval: 1000
base_lr: 0.01
lr_policy: "step"
gamma: 0.1
stepsize: 100000
display: 20
max_iter: 450000
momentum: 0.9
weight_decay: 0.0005
snapshot: 10000
snapshot_prefix: "models/alexnet/caffe_alexnet_train"
""",
    "cifar10_quick": """# CIFAR-10 quick solver (reference examples/cifar10 recipe)
net: "models/cifar10_quick/train_val.prototxt"
test_iter: 100
test_interval: 500
base_lr: 0.001
momentum: 0.9
weight_decay: 0.004
lr_policy: "fixed"
display: 100
max_iter: 4000
snapshot: 4000
snapshot_prefix: "models/cifar10_quick/cifar10_quick"
""",
    "googlenet": """# GoogLeNet solver (reference models/bvlc_googlenet recipe)
net: "models/googlenet/train_val.prototxt"
test_iter: 1000
test_interval: 4000
base_lr: 0.01
lr_policy: "poly"
power: 0.5
display: 40
max_iter: 2400000
momentum: 0.9
weight_decay: 0.0002
snapshot: 40000
snapshot_prefix: "models/googlenet/bvlc_googlenet"
""",
    "caffenet": """# CaffeNet solver (reference bvlc_reference_caffenet recipe)
net: "models/caffenet/train_val.prototxt"
test_iter: 1000
test_interval: 1000
base_lr: 0.01
lr_policy: "step"
gamma: 0.1
stepsize: 100000
display: 20
max_iter: 450000
momentum: 0.9
weight_decay: 0.0005
snapshot: 10000
snapshot_prefix: "models/caffenet/caffenet_train"
""",
    "vgg16": """# VGG-16 solver (reference models/vgg16 recipe class)
net: "models/vgg16/train_val.prototxt"
test_iter: 1000
test_interval: 4000
base_lr: 0.01
lr_policy: "step"
gamma: 0.1
stepsize: 100000
display: 40
max_iter: 370000
momentum: 0.9
weight_decay: 0.0005
snapshot: 20000
snapshot_prefix: "models/vgg16/vgg16"
""",
    "resnet18": """# ResNet-18 solver (reference models/resnet18 recipe class)
net: "models/resnet18/train_val.prototxt"
test_iter: 1000
test_interval: 5000
base_lr: 0.1
lr_policy: "poly"
power: 1.0
display: 100
max_iter: 600000
momentum: 0.9
weight_decay: 0.0001
snapshot: 25000
snapshot_prefix: "models/resnet18/resnet18"
""",
    "resnet50": """# ResNet-50 solver (reference models/resnet50/solver.prototxt recipe:
# poly power=2, momentum 0.9, wd 1e-4; DGX-1-class batch-256 variant uses
# base_lr 0.2 with warmup)
net: "models/resnet50/train_val.prototxt"
test_iter: 1000
test_interval: 5000
base_lr: 0.1
lr_policy: "poly"
power: 2.0
rampup_interval: 5000
rampup_lr: 0.01
display: 100
max_iter: 600000
momentum: 0.9
weight_decay: 0.0001
snapshot: 25000
snapshot_prefix: "models/resnet50/resnet50"
""",
}


def main():
    out_root = os.path.dirname(os.path.abspath(__file__))
    nets = {
        "alexnet": alexnet(),
        "caffenet": caffenet(),
        "cifar10_quick": cifar10_quick(),
        "googlenet": googlenet(),
        "resnet18": resnet18(),
        "resnet50": resnet50(),
        "vgg16": vgg16(),
    }
    for name, spec in nets.items():
        d = os.path.join(out_root, name)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "train_val.prototxt"), "w") as f:
            f.write(spec.to_prototxt() + "\n")
        with open(os.path.join(d, "solver.prototxt"), "w") as f:
            f.write(SOLVERS[name])
        print(f"wrote models/{name}/")


if __name__ == "__main__":
    main()
