#!/usr/bin/env python
"""Model zoo generator — programmatically emits the prototxt zoo using
NetSpec (the reference keeps equivalent python generators in
models/modelBuilder/). Run from the repo root:

    python models/generate_models.py

Topologies follow the reference zoo: bvlc_alexnet, CIFAR-10 quick,
GoogLeNet (inception v1), ResNet-50 (bottleneck [3,4,6,3], NVCaffe
fused-scale BatchNorm). Inputs are Input layers (feed-based); the data
pipeline binds real datasets at run time.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from caffe_mpi_tpu.net_spec import L, NetSpec


def train_test_tail(n, logits, include_train_loss=True):
    n.loss = L.SoftmaxWithLoss(logits, n.label,
                               include=dict(phase="TRAIN"))
    n.accuracy = L.Accuracy(logits, n.label, include=dict(phase="TEST"))
    n.accuracy_top5 = L.Accuracy(logits, n.label, top_k=5,
                                 include=dict(phase="TEST"))


def conv_relu(bottom, nout, ks, stride=1, pad=0, group=1):
    c = L.Convolution(bottom, num_output=nout, kernel_size=ks, stride=stride,
                      pad=pad, group=group,
                      weight_filler=dict(type="gaussian", std=0.01),
                      bias_filler=dict(type="constant"),
                      param=[dict(lr_mult=1, decay_mult=1),
                             dict(lr_mult=2, decay_mult=0)])
    return c, L.ReLU(c, in_place=True)


def alexnet(batch=256):
    """bvlc_alexnet topology (reference models/bvlc_alexnet)."""
    n = NetSpec("AlexNet")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 227, 227]), dict(dim=[batch])]))
    n.conv1, n.relu1 = conv_relu(n.data, 96, 11, stride=4)
    n.norm1 = L.LRN(n.relu1, local_size=5, alpha=1e-4, beta=0.75)
    n.pool1 = L.Pooling(n.norm1, pool="MAX", kernel_size=3, stride=2)
    n.conv2, n.relu2 = conv_relu(n.pool1, 256, 5, pad=2, group=2)
    n.norm2 = L.LRN(n.relu2, local_size=5, alpha=1e-4, beta=0.75)
    n.pool2 = L.Pooling(n.norm2, pool="MAX", kernel_size=3, stride=2)
    n.conv3, n.relu3 = conv_relu(n.pool2, 384, 3, pad=1)
    n.conv4, n.relu4 = conv_relu(n.relu3, 384, 3, pad=1, group=2)
    n.conv5, n.relu5 = conv_relu(n.relu4, 256, 3, pad=1, group=2)
    n.pool5 = L.Pooling(n.relu5, pool="MAX", kernel_size=3, stride=2)
    n.fc6 = L.InnerProduct(n.pool5, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant", value=0.1))
    n.relu6 = L.ReLU(n.fc6, in_place=True)
    n.drop6 = L.Dropout(n.fc6, dropout_ratio=0.5, in_place=True)
    n.fc7 = L.InnerProduct(n.fc6, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant", value=0.1))
    n.relu7 = L.ReLU(n.fc7, in_place=True)
    n.drop7 = L.Dropout(n.fc7, dropout_ratio=0.5, in_place=True)
    n.fc8 = L.InnerProduct(n.fc7, num_output=1000,
                           weight_filler=dict(type="gaussian", std=0.01),
                           bias_filler=dict(type="constant"))
    train_test_tail(n, n.fc8)
    return n


def cifar10_quick(batch=100):
    """CIFAR-10 quick (reference examples/cifar10)."""
    n = NetSpec("CIFAR10_quick")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 32, 32]), dict(dim=[batch])]))
    n.conv1 = L.Convolution(n.data, num_output=32, kernel_size=5, pad=2,
                            weight_filler=dict(type="gaussian", std=0.0001),
                            param=[dict(lr_mult=1), dict(lr_mult=2)])
    n.pool1 = L.Pooling(n.conv1, pool="MAX", kernel_size=3, stride=2)
    n.relu1 = L.ReLU(n.pool1, in_place=True)
    n.conv2 = L.Convolution(n.pool1, num_output=32, kernel_size=5, pad=2,
                            weight_filler=dict(type="gaussian", std=0.01),
                            param=[dict(lr_mult=1), dict(lr_mult=2)])
    n.relu2 = L.ReLU(n.conv2, in_place=True)
    n.pool2 = L.Pooling(n.conv2, pool="AVE", kernel_size=3, stride=2)
    n.conv3 = L.Convolution(n.pool2, num_output=64, kernel_size=5, pad=2,
                            weight_filler=dict(type="gaussian", std=0.01),
                            param=[dict(lr_mult=1), dict(lr_mult=2)])
    n.relu3 = L.ReLU(n.conv3, in_place=True)
    n.pool3 = L.Pooling(n.conv3, pool="AVE", kernel_size=3, stride=2)
    n.ip1 = L.InnerProduct(n.pool3, num_output=64,
                           weight_filler=dict(type="gaussian", std=0.1),
                           param=[dict(lr_mult=1), dict(lr_mult=2)])
    n.ip2 = L.InnerProduct(n.ip1, num_output=10,
                           weight_filler=dict(type="gaussian", std=0.1),
                           param=[dict(lr_mult=1), dict(lr_mult=2)])
    train_test_tail(n, n.ip2)
    return n


def inception(n, name, bottom, o1, o3r, o3, o5r, o5, op):
    """GoogLeNet inception module (reference layer names:
    inception_Xy/1x1 etc., so reference .caffemodel weights load by name)."""
    def cr(branch, b, nout, ks, pad=0):
        c = L.Convolution(b, num_output=nout, kernel_size=ks, pad=pad,
                          weight_filler=dict(type="xavier"),
                          bias_filler=dict(type="constant", value=0.2),
                          param=[dict(lr_mult=1, decay_mult=1),
                                 dict(lr_mult=2, decay_mult=0)])
        r = L.ReLU(c, in_place=True)
        setattr(n, f"{name}/{branch}", c)
        setattr(n, f"{name}/relu_{branch}", r)
        return r

    c1 = cr("1x1", bottom, o1, 1)
    c3r = cr("3x3_reduce", bottom, o3r, 1)
    c3 = cr("3x3", c3r, o3, 3, pad=1)
    c5r = cr("5x5_reduce", bottom, o5r, 1)
    c5 = cr("5x5", c5r, o5, 5, pad=2)
    pool = L.Pooling(bottom, pool="MAX", kernel_size=3, stride=1, pad=1)
    setattr(n, f"{name}/pool", pool)
    cp = cr("pool_proj", pool, op, 1)
    out = L.Concat(c1, c3, c5, cp)
    setattr(n, f"{name}/output", out)
    return out


def _googlenet_aux(n, prefix, bottom, label):
    """Aux classifier head (reference loss1/* and loss2/*)."""
    pool = L.Pooling(bottom, pool="AVE", kernel_size=5, stride=3)
    setattr(n, f"{prefix}/ave_pool", pool)
    c = L.Convolution(pool, num_output=128, kernel_size=1,
                      weight_filler=dict(type="xavier"),
                      bias_filler=dict(type="constant", value=0.2),
                      param=[dict(lr_mult=1, decay_mult=1),
                             dict(lr_mult=2, decay_mult=0)])
    setattr(n, f"{prefix}/conv", c)
    setattr(n, f"{prefix}/relu_conv", L.ReLU(c, in_place=True))
    fc = L.InnerProduct(c, num_output=1024,
                        weight_filler=dict(type="xavier"),
                        bias_filler=dict(type="constant", value=0.2),
                        param=[dict(lr_mult=1, decay_mult=1),
                               dict(lr_mult=2, decay_mult=0)])
    setattr(n, f"{prefix}/fc", fc)
    setattr(n, f"{prefix}/relu_fc", L.ReLU(fc, in_place=True))
    setattr(n, f"{prefix}/drop_fc", L.Dropout(fc, dropout_ratio=0.7,
                                              in_place=True))
    cls = L.InnerProduct(fc, num_output=1000,
                         weight_filler=dict(type="xavier"),
                         bias_filler=dict(type="constant"),
                         param=[dict(lr_mult=1, decay_mult=1),
                                dict(lr_mult=2, decay_mult=0)])
    setattr(n, f"{prefix}/classifier", cls)
    setattr(n, f"{prefix}/loss", L.SoftmaxWithLoss(
        cls, label, loss_weight=0.3, include=dict(phase="TRAIN")))
    setattr(n, f"{prefix}/top-1", L.Accuracy(cls, label,
                                             include=dict(phase="TEST")))
    setattr(n, f"{prefix}/top-5", L.Accuracy(cls, label, top_k=5,
                                             include=dict(phase="TEST")))


def googlenet(batch=128):
    """bvlc_googlenet (reference models/bvlc_googlenet/train_val.prototxt):
    9 inception modules, loss1/loss2 aux heads at weight 0.3, reference
    layer names throughout."""
    n = NetSpec("GoogLeNet")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 224, 224]), dict(dim=[batch])]))

    def cr(name, b, nout, ks, stride=1, pad=0):
        c = L.Convolution(b, num_output=nout, kernel_size=ks, stride=stride,
                          pad=pad, weight_filler=dict(type="xavier"),
                          bias_filler=dict(type="constant", value=0.2),
                          param=[dict(lr_mult=1, decay_mult=1),
                                 dict(lr_mult=2, decay_mult=0)])
        r = L.ReLU(c, in_place=True)
        setattr(n, name, c)
        setattr(n, f"{name.rsplit('/', 1)[0]}/relu_{name.rsplit('/', 1)[1]}", r)
        return r

    x = cr("conv1/7x7_s2", n.data, 64, 7, stride=2, pad=3)
    setattr(n, "pool1/3x3_s2", L.Pooling(x, pool="MAX", kernel_size=3, stride=2))
    setattr(n, "pool1/norm1", L.LRN(getattr(n, "pool1/3x3_s2"),
                                    local_size=5, alpha=1e-4, beta=0.75))
    x = cr("conv2/3x3_reduce", getattr(n, "pool1/norm1"), 64, 1)
    x = cr("conv2/3x3", x, 192, 3, pad=1)
    setattr(n, "conv2/norm2", L.LRN(x, local_size=5, alpha=1e-4, beta=0.75))
    setattr(n, "pool2/3x3_s2", L.Pooling(getattr(n, "conv2/norm2"),
                                         pool="MAX", kernel_size=3, stride=2))
    x = inception(n, "inception_3a", getattr(n, "pool2/3x3_s2"),
                  64, 96, 128, 16, 32, 32)
    x = inception(n, "inception_3b", x, 128, 128, 192, 32, 96, 64)
    setattr(n, "pool3/3x3_s2", L.Pooling(x, pool="MAX", kernel_size=3, stride=2))
    x = inception(n, "inception_4a", getattr(n, "pool3/3x3_s2"),
                  192, 96, 208, 16, 48, 64)
    _googlenet_aux(n, "loss1", x, n.label)
    x = inception(n, "inception_4b", x, 160, 112, 224, 24, 64, 64)
    x = inception(n, "inception_4c", x, 128, 128, 256, 24, 64, 64)
    x = inception(n, "inception_4d", x, 112, 144, 288, 32, 64, 64)
    _googlenet_aux(n, "loss2", x, n.label)
    x = inception(n, "inception_4e", x, 256, 160, 320, 32, 128, 128)
    setattr(n, "pool4/3x3_s2", L.Pooling(x, pool="MAX", kernel_size=3, stride=2))
    x = inception(n, "inception_5a", getattr(n, "pool4/3x3_s2"),
                  256, 160, 320, 32, 128, 128)
    x = inception(n, "inception_5b", x, 384, 192, 384, 48, 128, 128)
    setattr(n, "pool5/7x7_s1", L.Pooling(x, pool="AVE", kernel_size=7, stride=1))
    setattr(n, "pool5/drop_7x7_s1", L.Dropout(getattr(n, "pool5/7x7_s1"),
                                              dropout_ratio=0.4, in_place=True))
    cls = L.InnerProduct(getattr(n, "pool5/7x7_s1"), num_output=1000,
                         weight_filler=dict(type="xavier"),
                         bias_filler=dict(type="constant"),
                         param=[dict(lr_mult=1, decay_mult=1),
                                dict(lr_mult=2, decay_mult=0)])
    setattr(n, "loss3/classifier", cls)
    setattr(n, "loss3/loss3", L.SoftmaxWithLoss(
        cls, n.label, include=dict(phase="TRAIN")))
    setattr(n, "loss3/top-1", L.Accuracy(cls, n.label,
                                         include=dict(phase="TEST")))
    setattr(n, "loss3/top-5", L.Accuracy(cls, n.label, top_k=5,
                                         include=dict(phase="TEST")))
    return n


def _resnet(n, batch, stages, bottleneck):
    """Shared ResNet body emitter with the reference's layer names
    (res{stage}.{block}.conv{i} / .skipConv / .sum, X/bn, fc — see
    models/resnet50/train_val.prototxt): fused scale_bias BN, eps 1e-4,
    msra fillers, stride on the block's first conv."""
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 224, 224]), dict(dim=[batch])]))

    def cb(name, b, nout, ks, stride=1, pad=0, relu=True):
        return conv_bn_relu(n, name, b, nout, ks, stride=stride, pad_h=pad,
                            filler="msra", relu=relu)

    x = cb("conv1", n.data, 64, 7, stride=2, pad=3)
    n.pool1 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    x = n.pool1
    for si, (nout, blocks) in enumerate(stages):
        for bi in range(1, blocks + 1):
            prefix = f"res{si + 2}.{bi}"
            stride = 2 if (si > 0 and bi == 1) else 1
            x = bottleneck(n, prefix, x, nout, stride, cb,
                           project=(bi == 1))
    n.pool5 = L.Pooling(x, pool="AVE", global_pooling=True)
    n.fc = L.InnerProduct(n.pool5, num_output=1000,
                          weight_filler=dict(type="msra"),
                          bias_filler=dict(type="constant"),
                          param=[dict(lr_mult=1, decay_mult=1),
                                 dict(lr_mult=2, decay_mult=0)])
    train_test_tail(n, n.fc)
    return n


def _bottleneck50(n, prefix, b, nout, stride, cb, project):
    if project:
        sc = cb(f"{prefix}.skipConv", b, nout * 4, 1, stride=stride,
                relu=False)
    else:
        sc = b
    x = cb(f"{prefix}.conv1", b, nout, 1, stride=stride)
    x = cb(f"{prefix}.conv2", x, nout, 3, pad=1)
    x = cb(f"{prefix}.conv3", x, nout * 4, 1, relu=False)
    s = L.Eltwise(x, sc, operation="SUM")
    setattr(n, f"{prefix}.sum", s)
    r = L.ReLU(s, in_place=True)
    setattr(n, f"{prefix}.relu", r)
    return r


def _basicblock18(n, prefix, b, nout, stride, cb, project):
    project = project and (stride != 1 or nout != 64)
    if project:
        sc = cb(f"{prefix}.skipConv", b, nout, 1, stride=stride, relu=False)
    else:
        sc = b
    x = cb(f"{prefix}.conv1", b, nout, 3, stride=stride, pad=1)
    x = cb(f"{prefix}.conv2", x, nout, 3, pad=1, relu=False)
    s = L.Eltwise(x, sc, operation="SUM")
    setattr(n, f"{prefix}.sum", s)
    r = L.ReLU(s, in_place=True)
    setattr(n, f"{prefix}.relu", r)
    return r


def resnet50(batch=32):
    """ResNet-50 (reference models/resnet50/train_val.prototxt): bottleneck
    [3,4,6,3] with reference layer names so reference weights load."""
    return _resnet(NetSpec("ResNet50"), batch,
                   [(64, 3), (128, 4), (256, 6), (512, 3)], _bottleneck50)


def resnet18(batch=64):
    """ResNet-18 (reference models/resnet18/train_val.prototxt): basic
    blocks [2,2,2,2], projection only on downsampling stages."""
    return _resnet(NetSpec("ResNet18"), batch,
                   [(64, 2), (128, 2), (256, 2), (512, 2)], _basicblock18)


def conv_bn_relu(n, name, bottom, nout, kh, kw=None, stride=1, pad_h=0,
                 pad_w=None, group=1, eps=1e-4, filler="xavier", relu=True):
    """conv (bias-free) -> BatchNorm (separate top, fused scale/bias,
    eps 1e-4 like the reference BN zoo models) -> in-place ReLU.
    Shared by alexnet_bn / inception_v3 / cifar10_nv generators."""
    kw = kh if kw is None else kw
    pad_w = pad_h if pad_w is None else pad_w
    kwargs = dict(num_output=nout, bias_term=False,
                  weight_filler=dict(type=filler),
                  param=[dict(lr_mult=1, decay_mult=1)])
    if kh == kw:
        kwargs.update(kernel_size=kh)
    else:
        kwargs.update(kernel_h=kh, kernel_w=kw)
    if stride != 1:
        kwargs.update(stride=stride)
    if pad_h == pad_w:
        if pad_h:
            kwargs.update(pad=pad_h)
    else:
        kwargs.update(pad_h=pad_h, pad_w=pad_w)
    if group != 1:
        kwargs.update(group=group)
    c = L.Convolution(bottom, **kwargs)
    bn = L.BatchNorm(c, scale_bias=True, eps=eps,
                     moving_average_fraction=0.9)
    setattr(n, name, c)
    setattr(n, f"{name}/bn", bn)
    if not relu:
        return bn
    r = L.ReLU(bn, in_place=True)
    setattr(n, f"{name}/relu", r)
    return r


def alexnet_bn(batch=256):
    """AlexNet with BatchNorm after each conv (reference models/alexnet_bn;
    BN eps 1e-4 per its train_val.prototxt)."""
    n = NetSpec("AlexNet_BN")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 227, 227]), dict(dim=[batch])]))

    def cbr(name, b, nout, ks, stride=1, pad=0, group=1):
        return conv_bn_relu(n, name, b, nout, ks, stride=stride, pad_h=pad,
                            group=group, filler="msra")

    x = cbr("conv1", n.data, 96, 11, stride=4)
    n.pool1 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    x = cbr("conv2", n.pool1, 256, 5, pad=2, group=2)
    n.pool2 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    x = cbr("conv3", n.pool2, 384, 3, pad=1)
    x = cbr("conv4", x, 384, 3, pad=1, group=2)
    x = cbr("conv5", x, 256, 3, pad=1, group=2)
    n.pool5 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    n.fc6 = L.InnerProduct(n.pool5, num_output=4096,
                           weight_filler=dict(type="msra"),
                           bias_filler=dict(type="constant"))
    n.relu6 = L.ReLU(n.fc6, in_place=True)
    n.drop6 = L.Dropout(n.fc6, dropout_ratio=0.5, in_place=True)
    n.fc7 = L.InnerProduct(n.fc6, num_output=4096,
                           weight_filler=dict(type="msra"),
                           bias_filler=dict(type="constant"))
    n.relu7 = L.ReLU(n.fc7, in_place=True)
    n.drop7 = L.Dropout(n.fc7, dropout_ratio=0.5, in_place=True)
    n.fc8 = L.InnerProduct(n.fc7, num_output=1000,
                           weight_filler=dict(type="gaussian", std=0.01),
                           bias_filler=dict(type="constant"))
    train_test_tail(n, n.fc8)
    return n


def alexnet_owt(batch=256):
    """AlexNet "One Weird Trick" variant (reference models/alexnet_owt):
    single-tower — no LRN, no grouped convolutions; otherwise the
    bvlc_alexnet channel plan."""
    n = NetSpec("AlexNet-OWT")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 227, 227]), dict(dim=[batch])]))
    n.conv1, n.relu1 = conv_relu(n.data, 96, 11, stride=4)
    n.pool1 = L.Pooling(n.relu1, pool="MAX", kernel_size=3, stride=2)
    n.conv2, n.relu2 = conv_relu(n.pool1, 256, 5, pad=2)
    n.pool2 = L.Pooling(n.relu2, pool="MAX", kernel_size=3, stride=2)
    n.conv3, n.relu3 = conv_relu(n.pool2, 384, 3, pad=1)
    n.conv4, n.relu4 = conv_relu(n.relu3, 384, 3, pad=1)
    n.conv5, n.relu5 = conv_relu(n.relu4, 256, 3, pad=1)
    n.pool5 = L.Pooling(n.relu5, pool="MAX", kernel_size=3, stride=2)
    n.fc6 = L.InnerProduct(n.pool5, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant", value=0.1))
    n.relu6 = L.ReLU(n.fc6, in_place=True)
    n.drop6 = L.Dropout(n.fc6, dropout_ratio=0.5, in_place=True)
    n.fc7 = L.InnerProduct(n.fc6, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant", value=0.1))
    n.relu7 = L.ReLU(n.fc7, in_place=True)
    n.drop7 = L.Dropout(n.fc7, dropout_ratio=0.5, in_place=True)
    n.fc8 = L.InnerProduct(n.fc7, num_output=1000,
                           weight_filler=dict(type="gaussian", std=0.01),
                           bias_filler=dict(type="constant"))
    train_test_tail(n, n.fc8)
    return n


def inception_v2(batch=32):
    """Inception-v2 / BN-GoogLeNet (reference models/inception_v2/
    train_val.prototxt): GoogLeNet shape with BatchNorm (separate /bn top,
    fused scale+bias, eps 1e-4, maf 0.9) after every conv, the 5x5 branch
    conv named '5x5b', stride-2 reduction blocks 3c/4e (no 1x1 branch,
    MAX pool, no pool_proj), 5b's block pool is MAX, aux heads after
    3c and 4e at loss_weight 0.3. Reference layer names throughout so
    reference .caffemodel weights load by name."""
    n = NetSpec("Inception_v2")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 224, 224]), dict(dim=[batch])]))

    def cbr(name, b, nout, ks, stride=1, pad=0):
        bn = conv_bn_relu(n, name, b, nout, ks, stride=stride, pad_h=pad,
                          relu=False)
        r = L.ReLU(bn, in_place=True)
        setattr(n, f"{name}/bn/relu", r)
        return r

    def block(name, bottom, o1, o3r, o3, o5r, o5, op, pool="AVE"):
        c1 = cbr(f"{name}/1x1", bottom, o1, 1)
        c3r = cbr(f"{name}/3x3_reduce", bottom, o3r, 1)
        c3 = cbr(f"{name}/3x3", c3r, o3, 3, pad=1)
        c5r = cbr(f"{name}/5x5_reduce", bottom, o5r, 1)
        c5 = cbr(f"{name}/5x5b", c5r, o5, 5, pad=2)
        p = L.Pooling(bottom, pool=pool, kernel_size=3, stride=1, pad=1)
        setattr(n, f"{name}/pool", p)
        cp = cbr(f"{name}/pool_proj", p, op, 1)
        out = L.Concat(c1, c3, c5, cp)
        setattr(n, f"{name}/output", out)
        return out

    def reduce_block(name, bottom, o3r, o3, o5r, o5):
        """Stride-2 grid reduction: 3x3 and 5x5b branches at stride 2 +
        a MAX-pool passthrough; no 1x1/pool_proj branches."""
        c3r = cbr(f"{name}/3x3_reduce", bottom, o3r, 1)
        c3 = cbr(f"{name}/3x3", c3r, o3, 3, stride=2, pad=1)
        c5r = cbr(f"{name}/5x5_reduce", bottom, o5r, 1)
        c5 = cbr(f"{name}/5x5b", c5r, o5, 5, stride=2, pad=2)
        p = L.Pooling(bottom, pool="MAX", kernel_size=3, stride=2)
        setattr(n, f"{name}/pool", p)
        out = L.Concat(c3, c5, p)
        setattr(n, f"{name}/output", out)
        return out

    def aux_head(prefix, pool_name, bottom):
        p = L.Pooling(bottom, pool="AVE", kernel_size=5, stride=3)
        setattr(n, pool_name, p)
        c = cbr(f"{prefix}/conv", p, 128, 1)
        fc = L.InnerProduct(c, num_output=1024,
                            weight_filler=dict(type="xavier"),
                            bias_filler=dict(type="constant"))
        setattr(n, f"{prefix}/fc", fc)
        setattr(n, f"{prefix}/fc/relu", L.ReLU(fc, in_place=True))
        cls = L.InnerProduct(fc, num_output=1000,
                             weight_filler=dict(type="xavier"),
                             bias_filler=dict(type="constant"))
        setattr(n, f"{prefix}/classifier", cls)
        setattr(n, f"{prefix}/loss", L.SoftmaxWithLoss(
            cls, n.label, loss_weight=0.3, include=dict(phase="TRAIN")))
        prob = L.Softmax(cls, include=dict(phase="TEST"))
        setattr(n, f"{prefix}/prob", prob)
        setattr(n, f"{prefix}/top-1", L.Accuracy(
            prob, n.label, include=dict(phase="TEST")))
        setattr(n, f"{prefix}/top-5", L.Accuracy(
            prob, n.label, top_k=5, include=dict(phase="TEST")))

    x = cbr("conv1/7x7_s2", n.data, 64, 7, stride=2, pad=3)
    p1 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    setattr(n, "pool1/3x3_s2", p1)
    x = cbr("conv2/3x3_reduce", p1, 64, 1)
    x = cbr("conv2/3x3", x, 192, 3, pad=1)
    p2 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    setattr(n, "pool2/3x3_s2", p2)

    x = block("inception_3a", p2, 64, 64, 64, 64, 96, 32)
    x = block("inception_3b", x, 64, 64, 96, 64, 96, 64)
    x = reduce_block("inception_3c", x, 128, 160, 64, 96)
    aux_head("loss1", "pool3/5x5_s3", x)
    x = block("inception_4a", x, 224, 64, 96, 96, 128, 128)
    x = block("inception_4b", x, 192, 96, 128, 96, 128, 128)
    x = block("inception_4c", x, 160, 128, 160, 128, 160, 96)
    x = block("inception_4d", x, 96, 128, 192, 160, 192, 96)
    x = reduce_block("inception_4e", x, 128, 192, 192, 256)
    aux_head("loss2", "pool4/5x5_s3", x)
    x = block("inception_5a", x, 352, 192, 320, 160, 224, 128)
    x = block("inception_5b", x, 352, 192, 320, 192, 224, 128, pool="MAX")

    p5 = L.Pooling(x, pool="AVE", kernel_size=7, stride=1)
    setattr(n, "pool5/7x7_s1", p5)
    cls = L.InnerProduct(p5, num_output=1000,
                         weight_filler=dict(type="xavier"),
                         bias_filler=dict(type="constant"))
    setattr(n, "loss3/classifier", cls)
    n.loss = L.SoftmaxWithLoss(cls, n.label)
    setattr(n, "accuracy/top-1", L.Accuracy(cls, n.label,
                                            include=dict(phase="TEST")))
    setattr(n, "accuracy/top-5", L.Accuracy(cls, n.label, top_k=5,
                                            include=dict(phase="TEST")))
    return n


def inception_v3(batch=32):
    """Inception v3, faithful to reference models/inception_v3/train_val
    .prototxt: its NVCaffe stem (conv4=80 3x3, conv5=192 3x3/s2, conv6=288,
    ONE stem maxpool), blocks 3A-3C / 4A-4E (ch7 128,160,160,192,192) /
    5A-5B, reductions 3R/4R, aux heads loss1/loss2 (weight 0.3) after the
    reductions, AVE k7 tail pool, reference layer names (e.g. 3A/p2_3x3)."""
    n = NetSpec("InceptionV3")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 299, 299]), dict(dim=[batch])]))

    def cbr(name, b, nout, kh, kw=None, stride=1, pad_h=0, pad_w=None):
        return conv_bn_relu(n, name, b, nout, kh, kw, stride=stride,
                            pad_h=pad_h, pad_w=pad_w)

    def block_a(p, x):
        b1 = cbr(f"{p}/p1_1x1", x, 64, 1)
        b2 = cbr(f"{p}/p2_3x3", cbr(f"{p}/p2_1x1", x, 64, 1), 96, 3, pad_h=1)
        b3 = cbr(f"{p}/p3_1x1", x, 48, 1)
        b3 = cbr(f"{p}/p3_3x3a", b3, 64, 3, pad_h=1)
        b3 = cbr(f"{p}/p3_3x3b", b3, 64, 3, pad_h=1)
        pool = L.Pooling(x, pool="AVE", kernel_size=3, stride=1, pad=1)
        setattr(n, f"{p}/p4_pool", pool)
        b4 = cbr(f"{p}/p4_1x1", pool, 64, 1)
        out = L.Concat(b1, b2, b3, b4)
        setattr(n, f"{p}/concat", out)
        return out

    def block_b(p, x, ch7):
        b1 = cbr(f"{p}/p1_1x1", x, 192, 1)
        b2 = cbr(f"{p}/p2_1x1", x, ch7, 1)
        b2 = cbr(f"{p}/p2_1x7", b2, ch7, 1, 7, pad_h=0, pad_w=3)
        b2 = cbr(f"{p}/p2_7x1", b2, 192, 7, 1, pad_h=3, pad_w=0)
        b3 = cbr(f"{p}/p3_1x1", x, ch7, 1)
        b3 = cbr(f"{p}/p3_1x7a", b3, ch7, 1, 7, pad_h=0, pad_w=3)
        b3 = cbr(f"{p}/p3_7x1a", b3, ch7, 7, 1, pad_h=3, pad_w=0)
        b3 = cbr(f"{p}/p3_1x7b", b3, ch7, 1, 7, pad_h=0, pad_w=3)
        b3 = cbr(f"{p}/p3_7x1b", b3, 192, 7, 1, pad_h=3, pad_w=0)
        pool = L.Pooling(x, pool="AVE", kernel_size=3, stride=1, pad=1)
        setattr(n, f"{p}/p4_pool", pool)
        b4 = cbr(f"{p}/p4_1x1", pool, 192, 1)
        out = L.Concat(b1, b2, b3, b4)
        setattr(n, f"{p}/concat", out)
        return out

    def block_c(p, x):
        b1 = cbr(f"{p}/p1_1x1", x, 320, 1)
        b2r = cbr(f"{p}/p2_1x1", x, 384, 1)
        b2a = cbr(f"{p}/p2_1x3", b2r, 384, 1, 3, pad_h=0, pad_w=1)
        b2b = cbr(f"{p}/p2_3x1", b2r, 384, 3, 1, pad_h=1, pad_w=0)
        b2 = L.Concat(b2a, b2b)
        setattr(n, f"{p}/p2_concat", b2)
        b3r = cbr(f"{p}/p3_3x3", cbr(f"{p}/p3_1x1", x, 448, 1), 384, 3,
                  pad_h=1)
        b3a = cbr(f"{p}/p3_1x3", b3r, 384, 1, 3, pad_h=0, pad_w=1)
        b3b = cbr(f"{p}/p3_3x1", b3r, 384, 3, 1, pad_h=1, pad_w=0)
        b3 = L.Concat(b3a, b3b)
        setattr(n, f"{p}/p3_concat", b3)
        pool = L.Pooling(x, pool="AVE", kernel_size=3, stride=1, pad=1)
        setattr(n, f"{p}/p4_pool", pool)
        b4 = cbr(f"{p}/p4_1x1", pool, 192, 1)
        out = L.Concat(b1, b2, b3, b4)
        setattr(n, f"{p}/concat", out)
        return out

    def aux_head(p, x):
        pool = L.Pooling(x, pool="AVE", kernel_size=5, stride=3)
        setattr(n, f"{p}/pool", pool)
        conv = cbr(f"{p}/conv", pool, 128, 1)
        fc1 = L.InnerProduct(conv, num_output=1024,
                             weight_filler=dict(type="xavier"),
                             bias_filler=dict(type="constant"))
        setattr(n, f"{p}/fc1", fc1)
        setattr(n, f"{p}/fc1_relu", L.ReLU(fc1, in_place=True))
        fc2 = L.InnerProduct(fc1, num_output=1000,
                             weight_filler=dict(type="xavier"),
                             bias_filler=dict(type="constant"))
        setattr(n, f"{p}/fc2", fc2)
        setattr(n, f"{p}/loss", L.SoftmaxWithLoss(fc2, n.label,
                                                  loss_weight=0.3))
        setattr(n, f"{p}/top-1", L.Accuracy(fc2, n.label,
                                            include=dict(phase="TEST")))
        setattr(n, f"{p}/top-5", L.Accuracy(fc2, n.label, top_k=5,
                                            include=dict(phase="TEST")))

    x = cbr("conv1", n.data, 32, 3, stride=2)           # 149
    x = cbr("conv2", x, 32, 3)                          # 147
    x = cbr("conv3", x, 64, 3, pad_h=1)                 # 147
    n.pool1 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)  # 73
    x = cbr("conv4", n.pool1, 80, 3)                    # 71
    x = cbr("conv5", x, 192, 3, stride=2)               # 35
    x = cbr("conv6", x, 288, 3, pad_h=1)                # 35
    for p in ("3A", "3B", "3C"):
        x = block_a(p, x)
    # 3R reduction -> 17x17
    r1 = cbr("3R/p1_1x1", x, 64, 1)
    r1 = cbr("3R/p1_3x3a", r1, 96, 3, pad_h=1)
    r1 = cbr("3R/p1_3x3b", r1, 96, 3, stride=2)
    r2 = cbr("3R/p2_3x3", x, 384, 3, stride=2)
    rp = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    setattr(n, "3R/p3_pool", rp)
    x = L.Concat(r1, r2, rp)
    setattr(n, "3R/concat", x)
    aux_head("loss1", x)
    for p, ch7 in zip(("4A", "4B", "4C", "4D", "4E"),
                      (128, 160, 160, 192, 192)):
        x = block_b(p, x, ch7)
    # 4R reduction -> 8x8
    r1 = cbr("4R/p1_1x1", x, 192, 1)
    r1 = cbr("4R/p1_3x3", r1, 320, 3, stride=2)
    r2 = cbr("4R/p2_1x1", x, 192, 1)
    r2 = cbr("4R/p2_1x7", r2, 192, 1, 7, pad_h=0, pad_w=3)
    r2 = cbr("4R/p2_7x1", r2, 192, 7, 1, pad_h=3, pad_w=0)
    r2 = cbr("4R/p2_3x3", r2, 192, 3, stride=2)
    rp = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    setattr(n, "4R/p3_pool", rp)
    x = L.Concat(r1, r2, rp)
    setattr(n, "4R/concat", x)
    aux_head("loss2", x)
    for p in ("5A", "5B"):
        x = block_c(p, x)
    pool = L.Pooling(x, pool="AVE", kernel_size=7, stride=1)
    setattr(n, "loss/pool", pool)
    fc = L.InnerProduct(pool, num_output=1000,
                        weight_filler=dict(type="xavier"),
                        bias_filler=dict(type="constant"))
    setattr(n, "loss/fc", fc)
    n.loss = L.SoftmaxWithLoss(fc, n.label)
    setattr(n, "accuracy/top-1", L.Accuracy(fc, n.label,
                                            include=dict(phase="TEST")))
    setattr(n, "accuracy/top-5", L.Accuracy(fc, n.label, top_k=5,
                                            include=dict(phase="TEST")))
    return n


def caffenet(batch=256, name="CaffeNet", classes=1000, head="fc8",
             head_lr=1):
    """bvlc_reference_caffenet: AlexNet variant with pool-before-norm
    (reference models/bvlc_reference_caffenet). head/classes/head_lr
    parameterize the classifier for finetuning recipes (the flickr-style
    net is this body with a fresh fc8_flickr head at 10x lr)."""
    n = NetSpec(name)
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 227, 227]), dict(dim=[batch])]))
    n.conv1, n.relu1 = conv_relu(n.data, 96, 11, stride=4)
    n.pool1 = L.Pooling(n.relu1, pool="MAX", kernel_size=3, stride=2)
    n.norm1 = L.LRN(n.pool1, local_size=5, alpha=1e-4, beta=0.75)
    n.conv2, n.relu2 = conv_relu(n.norm1, 256, 5, pad=2, group=2)
    n.pool2 = L.Pooling(n.relu2, pool="MAX", kernel_size=3, stride=2)
    n.norm2 = L.LRN(n.pool2, local_size=5, alpha=1e-4, beta=0.75)
    n.conv3, n.relu3 = conv_relu(n.norm2, 384, 3, pad=1)
    n.conv4, n.relu4 = conv_relu(n.relu3, 384, 3, pad=1, group=2)
    n.conv5, n.relu5 = conv_relu(n.relu4, 256, 3, pad=1, group=2)
    n.pool5 = L.Pooling(n.relu5, pool="MAX", kernel_size=3, stride=2)
    n.fc6 = L.InnerProduct(n.pool5, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant", value=1))
    n.relu6 = L.ReLU(n.fc6, in_place=True)
    n.drop6 = L.Dropout(n.fc6, dropout_ratio=0.5, in_place=True)
    n.fc7 = L.InnerProduct(n.fc6, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant", value=1))
    n.relu7 = L.ReLU(n.fc7, in_place=True)
    n.drop7 = L.Dropout(n.fc7, dropout_ratio=0.5, in_place=True)
    head_kw = {}
    if head_lr != 1:
        # finetuning: the fresh head learns 10x faster than the
        # pretrained body (reference models/finetune_flickr_style/
        # train_val.prototxt lr_mult 10/20 on fc8_flickr)
        head_kw["param"] = [dict(lr_mult=head_lr, decay_mult=1),
                            dict(lr_mult=2 * head_lr, decay_mult=0)]
    ip = L.InnerProduct(n.fc7, num_output=classes,
                        weight_filler=dict(type="gaussian", std=0.01),
                        bias_filler=dict(type="constant"), **head_kw)
    setattr(n, head, ip)
    train_test_tail(n, ip)
    return n


def finetune_flickr_style(batch=50):
    """CaffeNet body + fresh 20-way fc8_flickr head: `caffe train -solver
    models/finetune_flickr_style/solver.prototxt -weights
    models/caffenet/<caffenet>.caffemodel` loads every body layer by name
    and leaves the renamed head at its filler init — the reference's
    canonical finetuning workflow (reference
    models/finetune_flickr_style/train_val.prototxt, examples/
    finetune_flickr_style/readme.md; 20 Flickr style classes)."""
    return caffenet(batch=batch, name="FlickrStyleCaffeNet", classes=20,
                    head="fc8_flickr", head_lr=10)


def vgg16(batch=64):
    """VGG-16 (reference models/vgg16)."""
    n = NetSpec("VGG16")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 224, 224]), dict(dim=[batch])]))
    x = n.data
    cfg = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
    for bi, (reps, ch) in enumerate(cfg, start=1):
        for ri in range(1, reps + 1):
            c = L.Convolution(x, num_output=ch, kernel_size=3, pad=1,
                              weight_filler=dict(type="msra"),
                              bias_filler=dict(type="constant"),
                              param=[dict(lr_mult=1, decay_mult=1),
                                     dict(lr_mult=2, decay_mult=0)])
            r = L.ReLU(c, in_place=True)
            setattr(n, f"conv{bi}_{ri}", c)
            setattr(n, f"relu{bi}_{ri}", r)
            x = r
        p = L.Pooling(x, pool="MAX", kernel_size=2, stride=2)
        setattr(n, f"pool{bi}", p)
        x = p
    n.fc6 = L.InnerProduct(x, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant"))
    n.relu6 = L.ReLU(n.fc6, in_place=True)
    n.drop6 = L.Dropout(n.fc6, dropout_ratio=0.5, in_place=True)
    n.fc7 = L.InnerProduct(n.fc6, num_output=4096,
                           weight_filler=dict(type="gaussian", std=0.005),
                           bias_filler=dict(type="constant"))
    n.relu7 = L.ReLU(n.fc7, in_place=True)
    n.drop7 = L.Dropout(n.fc7, dropout_ratio=0.5, in_place=True)
    # the reference vgg16 names this LAYER "fc8-5" but its top blob "fc8"
    n.fc8 = L.InnerProduct(n.fc7, num_output=1000, layer_name="fc8-5",
                           weight_filler=dict(type="gaussian", std=0.01),
                           bias_filler=dict(type="constant"))
    train_test_tail(n, n.fc8)
    return n



def cifar10_nv(batch=128):
    """cifar10_nv (reference models/cifar10_nv/cifar10_nv_train_test
    .prototxt): all-convolutional — 3x [128 3x3] with BN on conv3, pool,
    3x [256 3x3] with BN on conv6, pool, 320 3x3 / 320 1x1 / 10 1x1 head,
    AVE k5 pool; 28x28 crops of CIFAR images."""
    n = NetSpec("CIFAR10_nv")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, 3, 28, 28]), dict(dim=[batch])]))

    def cr(name, b, nout, ks, pad=0):
        c = L.Convolution(b, num_output=nout, kernel_size=ks, pad=pad,
                          weight_filler=dict(type="xavier"),
                          bias_filler=dict(type="constant"),
                          param=[dict(lr_mult=1), dict(lr_mult=2)])
        r = L.ReLU(c, in_place=True)
        setattr(n, name, c)
        setattr(n, f"{name}_relu", r)
        return r

    def cbnr(name, b, nout, ks, pad=0):
        # bn'd convs (conv3/conv6): bias-free conv + BN eps 1e-4 + ReLU
        return conv_bn_relu(n, name, b, nout, ks, pad_h=pad)

    x = cr("conv1", n.data, 128, 3, pad=1)
    x = cr("conv2", x, 128, 3, pad=1)
    x = cbnr("conv3", x, 128, 3, pad=1)
    n.pool3 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    x = cr("conv4", n.pool3, 256, 3, pad=1)
    x = cr("conv5", x, 256, 3, pad=1)
    x = cbnr("conv6", x, 256, 3, pad=1)
    n.pool6 = L.Pooling(x, pool="MAX", kernel_size=3, stride=2)
    x = cr("conv7", n.pool6, 320, 3)
    x = cr("conv8", x, 320, 1)
    x = cr("conv9", x, 10, 1)
    n.pool9 = L.Pooling(x, pool="AVE", kernel_size=5)
    train_test_tail(n, n.pool9)
    return n


def rcnn(batch=10):
    """R-CNN classifier head (reference models/rcnn, ilsvrc13 200-way):
    CaffeNet body with an fc-rcnn scoring layer; deploy-style for use
    with the Detector wrapper."""
    spec = NetSpec("R-CNN-ilsvrc13")
    spec.data = L.Input(input_param=dict(
        shape=dict(dim=[batch, 3, 227, 227])))
    # reuse the caffenet body topology by regenerating it on spec
    prev = spec.data
    body = [("conv1", 96, 11, 4, 0, 1, True), ("conv2", 256, 5, 1, 2, 2, True),
            ("conv3", 384, 3, 1, 1, 1, False), ("conv4", 384, 3, 1, 1, 2, False),
            ("conv5", 256, 3, 1, 1, 2, True)]
    norms = {"conv1", "conv2"}
    for name, nout, ks, st, pad, grp, pool in body:
        c = L.Convolution(prev, num_output=nout, kernel_size=ks, stride=st,
                          pad=pad, group=grp,
                          weight_filler=dict(type="gaussian", std=0.01),
                          bias_filler=dict(type="constant"))
        r = L.ReLU(c, in_place=True)
        setattr(spec, name, c)
        setattr(spec, f"{name}_relu", r)
        prev = r
        if pool:
            p = L.Pooling(prev, pool="MAX", kernel_size=3, stride=2)
            setattr(spec, f"pool_{name}", p)
            prev = p
        if name in norms:
            nm = L.LRN(prev, local_size=5, alpha=1e-4, beta=0.75)
            setattr(spec, f"norm_{name}", nm)
            prev = nm
    spec.fc6 = L.InnerProduct(prev, num_output=4096,
                              weight_filler=dict(type="gaussian", std=0.005))
    spec.relu6 = L.ReLU(spec.fc6, in_place=True)
    spec.fc7 = L.InnerProduct(spec.fc6, num_output=4096,
                              weight_filler=dict(type="gaussian", std=0.005))
    spec.relu7 = L.ReLU(spec.fc7, in_place=True)
    setattr(spec, "fc-rcnn", L.InnerProduct(
        spec.fc7, num_output=200,
        weight_filler=dict(type="gaussian", std=0.01)))
    return spec


def transformer_lm(batch=8, seq=64, vocab=256, dim=128, heads=4,
                   n_blocks=2, ffn_hidden=256, moe_experts=4):
    """Decoder-only language model — the beyond-reference flagship for the
    long-context stack, expressed entirely in prototxt layer types:
    Embed + learnable positional bias, pre-LN blocks of causal Attention
    and FFN (one block's FFN is an MoE with a weighted aux-loss top),
    trailing LayerNorm, per-position classifier, spatial SoftmaxWithLoss.
    The reference (a CNN framework) has no analogue; every extension type
    used here (Attention/MoE/LayerNorm) is registered and gradchecked
    like the reference ops."""
    n = NetSpec("transformer_lm")
    n.tokens, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch, seq]), dict(dim=[batch, seq])]))
    n.embed = L.Embed(n.tokens, input_dim=vocab, num_output=dim,
                      bias_term=False,
                      weight_filler=dict(type="gaussian", std=0.02))
    n.pos = L.Parameter(ntop=1, parameter_param=dict(
        shape=dict(dim=[seq, dim])))
    # broadcast-add positions onto (N, S, C) starting at axis 1
    n.x0 = L.Bias(n.embed, n.pos, axis=1)
    x = n.x0
    for b in range(n_blocks):
        ln1 = L.LayerNorm(x)
        setattr(n, f"blk{b}/ln1", ln1)
        attn = L.Attention(ln1, num_heads=heads, causal=True,
                           weight_filler=dict(type="gaussian", std=0.02))
        setattr(n, f"blk{b}/attn", attn)
        res1 = L.Eltwise(x, attn)
        setattr(n, f"blk{b}/res1", res1)
        ln2 = L.LayerNorm(res1)
        setattr(n, f"blk{b}/ln2", ln2)
        if b == n_blocks - 1 and moe_experts:
            moe_y, moe_aux = L.MoE(ln2, ntop=2,
                                   loss_weight=[0.0, 0.01],
                                   moe_param=dict(num_experts=moe_experts,
                                                  hidden_dim=ffn_hidden,
                                                  capacity_factor=2.0))
            setattr(n, f"blk{b}/moe", moe_y)
            setattr(n, f"blk{b}/moe_aux", moe_aux)
            ffn = moe_y
        else:
            fc1 = L.InnerProduct(ln2, num_output=ffn_hidden, axis=2,
                                 weight_filler=dict(type="gaussian",
                                                    std=0.02))
            setattr(n, f"blk{b}/fc1", fc1)
            setattr(n, f"blk{b}/relu", L.ReLU(fc1, in_place=True))
            ffn = L.InnerProduct(fc1, num_output=dim, axis=2,
                                 weight_filler=dict(type="gaussian",
                                                    std=0.02))
            setattr(n, f"blk{b}/fc2", ffn)
        res2 = L.Eltwise(res1, ffn)
        setattr(n, f"blk{b}/res2", res2)
        x = res2
    n.ln_f = L.LayerNorm(x)
    n.logits = L.InnerProduct(n.ln_f, num_output=vocab, axis=2,
                              weight_filler=dict(type="gaussian", std=0.02))
    n.loss = L.SoftmaxWithLoss(n.logits, n.label,
                               softmax_param=dict(axis=2))
    n.accuracy = L.Accuracy(n.logits, n.label, axis=2,
                            include=dict(phase="TEST"))
    return n


def transformer_lm_pp_prototxt(batch=8, seq=64, vocab=256, dim=128, heads=4,
                               n_stages=4, micro_batches=4, ffn_hidden=256):
    """Pipeline-parallel transformer_lm variant: the trunk is ONE Pipeline
    layer whose repeated block is the pre-LN attention+FFN pair, so
    `caffe train -solver models/transformer_lm/solver_pp.prototxt -mesh
    data=N,model=4` trains with stage weights sharded one-per-device
    (layers/composite.py). Stages must be structurally identical, so this
    variant is homogeneous (no MoE block) and emitted as text rather than
    through NetSpec (which has no nested-block syntax)."""
    blk = f"""    layer {{ name: "ln1" type: "LayerNorm" bottom: "h" top: "n1" }}
    layer {{ name: "attn" type: "Attention" bottom: "n1" top: "a"
             attention_param {{ num_heads: {heads} causal: true
               weight_filler {{ type: "gaussian" std: 0.02 }} }} }}
    layer {{ name: "res1" type: "Eltwise" bottom: "h" bottom: "a" top: "r1" }}
    layer {{ name: "ln2" type: "LayerNorm" bottom: "r1" top: "n2" }}
    layer {{ name: "fc1" type: "InnerProduct" bottom: "n2" top: "f1"
             inner_product_param {{ num_output: {ffn_hidden} axis: 2
               weight_filler {{ type: "gaussian" std: 0.02 }} }} }}
    layer {{ name: "relu" type: "ReLU" bottom: "f1" top: "f1" }}
    layer {{ name: "fc2" type: "InnerProduct" bottom: "f1" top: "f2"
             inner_product_param {{ num_output: {dim} axis: 2
               weight_filler {{ type: "gaussian" std: 0.02 }} }} }}
    layer {{ name: "res2" type: "Eltwise" bottom: "r1" bottom: "f2"
             top: "out" }}"""
    return f"""name: "transformer_lm_pp"
layer {{ name: "tokens" type: "Input" top: "tokens" top: "label"
        input_param {{ shape {{ dim: {batch} dim: {seq} }}
                       shape {{ dim: {batch} dim: {seq} }} }} }}
layer {{ name: "embed" type: "Embed" bottom: "tokens" top: "embed"
        embed_param {{ input_dim: {vocab} num_output: {dim} bias_term: false
          weight_filler {{ type: "gaussian" std: 0.02 }} }} }}
layer {{ name: "pos" type: "Parameter" top: "pos"
        parameter_param {{ shape {{ dim: {seq} dim: {dim} }} }} }}
layer {{ name: "h" type: "Bias" bottom: "embed" bottom: "pos" top: "h"
        bias_param {{ axis: 1 }} }}
layer {{ name: "trunk" type: "Pipeline" bottom: "h" top: "hN"
        pipeline_param {{ num_stages: {n_stages}
          micro_batches: {micro_batches}
{blk} }} }}
layer {{ name: "ln_f" type: "LayerNorm" bottom: "hN" top: "ln_f" }}
layer {{ name: "logits" type: "InnerProduct" bottom: "ln_f" top: "logits"
        inner_product_param {{ num_output: {vocab} axis: 2
          weight_filler {{ type: "gaussian" std: 0.02 }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
        bottom: "label" top: "loss" softmax_param {{ axis: 2 }} }}
layer {{ name: "accuracy" type: "Accuracy" bottom: "logits" bottom: "label"
        top: "accuracy" accuracy_param {{ axis: 2 }}
        include {{ phase: TEST }} }}
"""


SOLVERS = {
    "transformer_lm": """# transformer_lm solver (beyond-reference demo model; Adam recipe)
net: "models/transformer_lm/train_val.prototxt"
test_iter: 16
test_interval: 1000
test_initialization: false
base_lr: 0.001
lr_policy: "fixed"
display: 100
max_iter: 10000
momentum: 0.9
momentum2: 0.999
type: "Adam"
snapshot: 10000
snapshot_prefix: "models/transformer_lm/transformer_lm"
""",
    "alexnet": """# AlexNet solver (reference models/bvlc_alexnet/solver.prototxt recipe)
net: "models/alexnet/train_val.prototxt"
test_iter: 1000
test_interval: 1000
base_lr: 0.01
lr_policy: "step"
gamma: 0.1
stepsize: 100000
display: 20
max_iter: 450000
momentum: 0.9
weight_decay: 0.0005
snapshot: 10000
snapshot_prefix: "models/alexnet/caffe_alexnet_train"
""",
    "cifar10_quick": """# CIFAR-10 quick solver (reference examples/cifar10 recipe)
net: "models/cifar10_quick/train_val.prototxt"
test_iter: 100
test_interval: 500
base_lr: 0.001
momentum: 0.9
weight_decay: 0.004
lr_policy: "fixed"
display: 100
max_iter: 4000
snapshot: 4000
snapshot_prefix: "models/cifar10_quick/cifar10_quick"
""",
    "googlenet": """# GoogLeNet solver (reference models/bvlc_googlenet recipe)
net: "models/googlenet/train_val.prototxt"
test_iter: 1000
test_interval: 4000
base_lr: 0.01
lr_policy: "poly"
power: 0.5
display: 40
max_iter: 2400000
momentum: 0.9
weight_decay: 0.0002
snapshot: 40000
snapshot_prefix: "models/googlenet/bvlc_googlenet"
""",
    "cifar10_nv": """# cifar10_nv solver (reference models/cifar10_nv/cifar10_nv_solver.prototxt)
net: "models/cifar10_nv/train_val.prototxt"
test_iter: 20
test_interval: 400
display: 100
max_iter: 100000
lr_policy: "poly"
base_lr: 0.01
power: 2
momentum: 0.9
weight_decay: 0.004
snapshot: 1000000
snapshot_prefix: "models/cifar10_nv/cifar10_nv"
snapshot_after_train: false
""",
    "alexnet_bn": """# AlexNet-BN solver (reference models/alexnet_bn/solver.prototxt)
net: "models/alexnet_bn/train_val.prototxt"
test_iter: 195
test_interval: 5000
test_initialization: false
display: 100
max_iter: 150000
lr_policy: "poly"
base_lr: 0.02
power: 2.0
momentum: 0.9
weight_decay: 0.0005
snapshot: 500000
snapshot_prefix: "models/alexnet_bn/alexnet_bn"
""",
    "inception_v3": """# Inception-v3 solver (reference models/inception_v3/solver.prototxt;
# DGX-1 batch-256 variant: max_iter 300000, base_lr 0.2)
net: "models/inception_v3/train_val.prototxt"
test_iter: 1563
test_interval: 20000
test_initialization: false
display: 100
max_iter: 2400000
base_lr: 0.05
lr_policy: "poly"
power: 2
momentum: 0.9
weight_decay: 0.0001
snapshot: 20000
snapshot_prefix: "models/inception_v3/inception_v3"
""",
    "alexnet_owt": """# AlexNet-OWT solver (reference models/alexnet_owt/solver.prototxt:
# poly power 2, base_lr 0.02 for B=1024, 100 epochs)
net: "models/alexnet_owt/train_val.prototxt"
test_iter: 195
test_interval: 5000
test_initialization: false
display: 100
max_iter: 125000
base_lr: 0.02
lr_policy: "poly"
power: 2.0
momentum: 0.9
weight_decay: 0.0005
snapshot: 500000
snapshot_prefix: "models/alexnet_owt/alexnet_owt"
""",
    "inception_v2": """# Inception-v2 solver (reference models/inception_v2/solver.prototxt:
# poly power 2; B=256 variant uses base_lr 0.2, max_iter 300000)
net: "models/inception_v2/train_val.prototxt"
test_iter: 1563
test_interval: 20000
test_initialization: false
display: 100
max_iter: 2400000
base_lr: 0.05
lr_policy: "poly"
power: 2.0
momentum: 0.9
weight_decay: 0.0002
snapshot: 20000
snapshot_prefix: "models/inception_v2/inception_v2"
""",
    "caffenet": """# CaffeNet solver (reference bvlc_reference_caffenet recipe)
net: "models/caffenet/train_val.prototxt"
test_iter: 1000
test_interval: 1000
base_lr: 0.01
lr_policy: "step"
gamma: 0.1
stepsize: 100000
display: 20
max_iter: 450000
momentum: 0.9
weight_decay: 0.0005
snapshot: 10000
snapshot_prefix: "models/caffenet/caffenet_train"
""",
    "finetune_flickr_style": """# Flickr-style finetuning solver (reference
# models/finetune_flickr_style/solver.prototxt: lr 10x lower than
# from-scratch, step decay closer-in, fresh head at lr_mult 10)
net: "models/finetune_flickr_style/train_val.prototxt"
test_iter: 100
test_interval: 1000
base_lr: 0.001
lr_policy: "step"
gamma: 0.1
stepsize: 20000
display: 20
max_iter: 100000
momentum: 0.9
weight_decay: 0.0005
snapshot: 10000
snapshot_prefix: "models/finetune_flickr_style/finetune_flickr_style"
""",
    "vgg16": """# VGG-16 solver (reference models/vgg16 recipe class)
net: "models/vgg16/train_val.prototxt"
test_iter: 1000
test_interval: 4000
base_lr: 0.01
lr_policy: "step"
gamma: 0.1
stepsize: 100000
display: 40
max_iter: 370000
momentum: 0.9
weight_decay: 0.0005
snapshot: 20000
snapshot_prefix: "models/vgg16/vgg16"
""",
    "resnet18": """# ResNet-18 solver (reference models/resnet18 recipe class)
net: "models/resnet18/train_val.prototxt"
test_iter: 1000
test_interval: 5000
base_lr: 0.1
lr_policy: "poly"
power: 1.0
display: 100
max_iter: 600000
momentum: 0.9
weight_decay: 0.0001
snapshot: 25000
snapshot_prefix: "models/resnet18/resnet18"
""",
    "resnet50": """# ResNet-50 solver (reference models/resnet50/solver.prototxt recipe:
# poly power=2, momentum 0.9, wd 1e-4; DGX-1-class batch-256 variant uses
# base_lr 0.2 with warmup)
net: "models/resnet50/train_val.prototxt"
test_iter: 1000
test_interval: 5000
base_lr: 0.1
lr_policy: "poly"
power: 2.0
rampup_interval: 5000
rampup_lr: 0.01
display: 100
max_iter: 600000
momentum: 0.9
weight_decay: 0.0001
snapshot: 25000
snapshot_prefix: "models/resnet50/resnet50"
""",
}


def make_deploy(train_val_path: str, batch: int = 10) -> str:
    """Derive a deploy net from a train_val file (reference zoo ships
    deploy.prototxt per model): drop phase-gated loss/accuracy layers and
    the label input, softmax the final classifier into 'prob'."""
    from caffe_mpi_tpu.proto import NetParameter, NetState, filter_net, normalize_net
    from caffe_mpi_tpu.proto.text_format import PbNode, PbEnum

    net = normalize_net(NetParameter.from_file(train_val_path))
    # keep only layers live in NEITHER-specific deploy sense: drop anything
    # phase-gated (losses, accuracies) and any loss-typed layer
    drop_types = {"SoftmaxWithLoss", "Accuracy", "EuclideanLoss", "HingeLoss",
                  "SigmoidCrossEntropyLoss", "ContrastiveLoss", "InfogainLoss",
                  "MultinomialLogisticLoss", "L1Loss"}
    kept = [lp for lp in net.layer
            if lp.type not in drop_types and not lp.include and not lp.exclude]
    consumed = {b for lp in kept for b in lp.bottom}
    produced = [t for lp in kept for t in lp.top]
    # classifier blob = last produced blob not consumed elsewhere
    final = [t for t in produced if t not in consumed][-1]
    # dead-branch elimination by reverse liveness (robust to in-place
    # relu/dropout self-loops): keep only layers reaching the classifier —
    # the reference deploy files likewise omit the aux branches
    live = {final}
    kept_rev = []
    for lp in reversed(kept):
        if lp.type == "Input" or any(t in live for t in lp.top):
            kept_rev.append(lp)
            live.update(lp.bottom)
    kept = list(reversed(kept_rev))

    root = PbNode()
    root.add("name", net.name)
    for lp in kept:
        node = lp.to_node()
        if lp.type == "Input":
            # single data input at deploy batch size (keep the net's own
            # first top name — image nets call it "data", the LM "tokens")
            first_top = lp.top[0]
            node.fields.pop("top", None)
            node.add("top", first_top)
            ip = PbNode()
            shape = PbNode()
            dims = lp.input_param.shape[0].dim
            for d in [batch] + [int(x) for x in dims[1:]]:
                shape.add("dim", d)
            ip.add("shape", shape)
            node.fields["input_param"] = [ip]
        root.add("layer", node)
    prob = PbNode()
    prob.add("name", "prob")
    prob.add("type", "Softmax")
    prob.add("bottom", final)
    prob.add("top", "prob")
    root.add("layer", prob)
    return root.to_text()


def main():
    out_root = os.path.dirname(os.path.abspath(__file__))
    nets = {
        "alexnet": alexnet(),
        "alexnet_bn": alexnet_bn(),
        "alexnet_owt": alexnet_owt(),
        "inception_v2": inception_v2(),
        "caffenet": caffenet(),
        "finetune_flickr_style": finetune_flickr_style(),
        "cifar10_quick": cifar10_quick(),
        "googlenet": googlenet(),
        "inception_v3": inception_v3(),
        "resnet18": resnet18(),
        "resnet50": resnet50(),
        "vgg16": vgg16(),
        "cifar10_nv": cifar10_nv(),
        "transformer_lm": transformer_lm(),
    }
    # deploy-only model (no solver): rcnn
    d = os.path.join(out_root, "rcnn")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "deploy.prototxt"), "w") as f:
        f.write(rcnn().to_prototxt() + "\n")
    print("wrote models/rcnn/ (deploy only)")

    # fp16 variants (reference models/resnet50/train_val_fp16.prototxt +
    # solver_fp16.prototxt): FLOAT16 -> bfloat16 on TPU, f32 master
    # weights, loss scaling
    # the reference ships fp16 variants for these families
    for name in ("resnet50", "resnet18", "alexnet", "alexnet_owt",
                 "googlenet", "inception_v2", "inception_v3", "vgg16"):
        d = os.path.join(out_root, name)
        base = open(os.path.join(d, "train_val.prototxt")).read()
        with open(os.path.join(d, "train_val_fp16.prototxt"), "w") as f:
            f.write("default_forward_type: FLOAT16\n"
                    "default_backward_type: FLOAT16\n"
                    "global_grad_scale: 1000\n" + base)
        solver = open(os.path.join(d, "solver.prototxt")).read()
        with open(os.path.join(d, "solver_fp16.prototxt"), "w") as f:
            f.write(solver.replace("train_val.prototxt",
                                   "train_val_fp16.prototxt"))
        print(f"wrote models/{name}/ fp16 variant")
    for name, spec in nets.items():
        d = os.path.join(out_root, name)
        os.makedirs(d, exist_ok=True)
        tv = os.path.join(d, "train_val.prototxt")
        with open(tv, "w") as f:
            f.write(spec.to_prototxt() + "\n")
        with open(os.path.join(d, "solver.prototxt"), "w") as f:
            f.write(SOLVERS[name])
        with open(os.path.join(d, "deploy.prototxt"), "w") as f:
            f.write(make_deploy(tv) + "\n")
        print(f"wrote models/{name}/")

    # transformer_lm model-parallel variants: PP trunk (Pipeline layer)
    # and SP attention (sequence_parallel: true), each launchable from one
    # `caffe train -mesh data=N,model=M` line
    d = os.path.join(out_root, "transformer_lm")
    with open(os.path.join(d, "train_val_pp.prototxt"), "w") as f:
        f.write(transformer_lm_pp_prototxt())
    base = open(os.path.join(d, "train_val.prototxt")).read()
    with open(os.path.join(d, "train_val_sp.prototxt"), "w") as f:
        f.write(base.replace("causal: true",
                             "causal: true\n    sequence_parallel: true"))
    solver = open(os.path.join(d, "solver.prototxt")).read()
    for variant in ("pp", "sp"):
        with open(os.path.join(d, f"solver_{variant}.prototxt"), "w") as f:
            # the second replace also renames the snapshot_prefix line
            # (it ends in transformer_lm")
            f.write(solver.replace("train_val.prototxt",
                                   f"train_val_{variant}.prototxt")
                    .replace("transformer_lm\"",
                             f"transformer_lm_{variant}\""))
    print("wrote models/transformer_lm/ pp + sp variants")


if __name__ == "__main__":
    main()
